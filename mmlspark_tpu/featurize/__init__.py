from .value_indexer import ValueIndexer, ValueIndexerModel, IndexToValue
from .clean_missing import CleanMissingData, CleanMissingDataModel
from .featurize import (Featurize, FeaturizeModel, CountSelector,
                        CountSelectorModel, DataConversion)
from .text import TextFeaturizer, TextFeaturizerModel

__all__ = ["ValueIndexer", "ValueIndexerModel", "IndexToValue",
           "CleanMissingData", "CleanMissingDataModel", "Featurize",
           "FeaturizeModel", "CountSelector", "CountSelectorModel",
           "DataConversion", "TextFeaturizer", "TextFeaturizerModel"]
