"""CleanMissingData: NaN imputation per column (reference: featurize/CleanMissingData.scala)."""
from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Param, Table, one_of


class CleanMissingData(Estimator):
    input_cols = Param("input_cols", "columns to impute", None)
    output_cols = Param("output_cols", "output columns (default: in place)", None)
    cleaning_mode = Param("cleaning_mode", "Mean|Median|Custom", "Mean",
                          validator=one_of("Mean", "Median", "Custom"))
    custom_value = Param("custom_value", "fill value for Custom mode", 0.0)

    def _fit(self, t: Table) -> "CleanMissingDataModel":
        cols = self.input_cols or [c for c in t.columns
                                   if np.issubdtype(t[c].dtype, np.floating)]
        fills = {}
        for c in cols:
            col = np.asarray(t[c], dtype=np.float64)
            ok = ~np.isnan(col)
            if self.cleaning_mode == "Mean":
                fills[c] = float(col[ok].mean()) if ok.any() else 0.0
            elif self.cleaning_mode == "Median":
                fills[c] = float(np.median(col[ok])) if ok.any() else 0.0
            else:
                fills[c] = float(self.custom_value)
        m = CleanMissingDataModel(input_cols=list(cols),
                                  output_cols=self.output_cols)
        m._fills = fills
        return m


class CleanMissingDataModel(Model):
    input_cols = Param("input_cols", "columns to impute", None)
    output_cols = Param("output_cols", "output columns", None)

    def __init__(self, **kw):
        super().__init__(**kw)
        self._fills = {}

    def _get_state(self):
        return {"fill_cols": np.asarray(list(self._fills.keys()), dtype=object),
                "fill_vals": np.asarray(list(self._fills.values()), np.float64)}

    def _set_state(self, s):
        self._fills = {str(k): float(v)
                       for k, v in zip(s["fill_cols"], s["fill_vals"])}

    def _transform(self, t: Table) -> Table:
        outs = self.output_cols or self.input_cols
        for cin, cout in zip(self.input_cols, outs):
            col = np.asarray(t[cin], dtype=np.float64)
            t = t.with_column(cout, np.where(np.isnan(col), self._fills[cin], col))
        return t
