"""Featurize: automatic per-column featurization into one dense features matrix.

Reference: featurize/Featurize.scala:27-88 — per input column the fitted
pipeline applies: numeric -> impute(mean); categorical (string or flagged
int) -> ValueIndexer then one-hot (or index passthrough); high-cardinality
strings -> murmur hashing into `num_features` slots (2^18 default, 2^12 for
tree learners); vector columns pass through; all assembled by a fast
assembler (FastVectorAssembler analog = one np.concatenate).
"""
from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Param, Table, Transformer
from ..core.params import one_of
from ..ops.hashing import hash_strings
from ..ops.sparse import DENSE_AUTO_LIMIT
from .clean_missing import CleanMissingData
from .value_indexer import ValueIndexer

DEFAULT_NUM_FEATURES = 1 << 18       # Featurize.scala:27
DEFAULT_NUM_FEATURES_TREES = 1 << 12  # Featurize.scala:29


class Featurize(Estimator):
    input_cols = Param("input_cols", "columns to featurize (default: all but label)", None)
    output_col = Param("output_col", "assembled features column", "features")
    label_col = Param("label_col", "label column excluded from features", "label")
    one_hot_encode_categoricals = Param(
        "one_hot_encode_categoricals", "one-hot vs index for categoricals", True)
    num_features = Param("num_features",
                         "hash slots for high-cardinality strings (0=auto: "
                         "2^12 dense tree default; set 2^18 for the linear "
                         "default, which auto-switches to sparse output)", 0)
    max_onehot_cardinality = Param(
        "max_onehot_cardinality", "index/one-hot below, hash above", 64)
    impute_missing = Param("impute_missing", "mean-impute numeric NaN", True)
    dense_output = Param(
        "dense_output",
        "auto | True | False — False emits sparse pair columns "
        "<out>_idx/<out>_val instead of a dense matrix; 'auto' goes sparse "
        "when the assembled width exceeds 2^14 (each row's nnz is "
        "schema-static, so the pair shape is (n, n_slots))", "auto",
        validator=one_of("auto", True, False))

    def _fit(self, t: Table) -> "FeaturizeModel":
        cols = self.input_cols or [c for c in t.columns if c != self.label_col]
        plans = []  # (col, kind, aux)
        nf_hash = self.num_features or DEFAULT_NUM_FEATURES_TREES
        imputer_cols = []
        for c in cols:
            arr = t[c]
            if arr.ndim == 2:
                plans.append((c, "vector", arr.shape[1]))
            elif np.issubdtype(arr.dtype, np.number):
                plans.append((c, "numeric", None))
                if self.impute_missing and np.issubdtype(arr.dtype, np.floating):
                    imputer_cols.append(c)
            else:  # strings / objects
                uniq = np.unique(arr.astype(str))
                if uniq.size <= self.max_onehot_cardinality:
                    idx = ValueIndexer(input_col=c, output_col=f"__{c}_idx").fit(t)
                    kind = "onehot" if self.one_hot_encode_categoricals else "index"
                    plans.append((c, kind, idx))
                else:
                    plans.append((c, "hash", nf_hash))
        imputer = (CleanMissingData(input_cols=imputer_cols).fit(t)
                   if imputer_cols else None)
        m = FeaturizeModel(output_col=self.output_col,
                           dense_output=self.dense_output)
        m._plans, m._imputer = plans, imputer
        return m


class FeaturizeModel(Model):
    output_col = Param("output_col", "assembled features column", "features")
    dense_output = Param("dense_output", "auto | True | False", "auto",
                         validator=one_of("auto", True, False))

    def __init__(self, **kw):
        super().__init__(**kw)
        self._plans, self._imputer = [], None

    # -- layout ------------------------------------------------------------
    def _plan_widths(self):
        """Logical feature width per plan (vectors: length; numeric/index: 1;
        onehot: level count; hash: table size)."""
        out = []
        for c, kind, aux in self._plans:
            if kind == "vector":
                out.append(int(aux))
            elif kind in ("numeric", "index"):
                out.append(1)
            elif kind == "onehot":
                out.append(len(aux._levels))
            elif kind == "hash":
                out.append(int(aux))
        return out

    @property
    def num_output_features(self) -> int:
        """Total logical feature width of the assembled vector."""
        return sum(self._plan_widths())

    @property
    def _dense(self) -> bool:
        d = self.dense_output
        if d is True:
            return True
        if d is False:
            return False
        return self.num_output_features <= DENSE_AUTO_LIMIT

    # persistence: encode plans as parallel object arrays + nested stages
    def _get_state(self):
        state = {
            "plan_cols": np.asarray([p[0] for p in self._plans], dtype=object),
            "plan_kinds": np.asarray([p[1] for p in self._plans], dtype=object),
            "plan_dims": np.asarray(
                [p[2] if isinstance(p[2], int) else -1 for p in self._plans],
                np.int64),
        }
        for i, (c, kind, aux) in enumerate(self._plans):
            if kind in ("onehot", "index"):
                state[f"levels_{i}"] = np.asarray(aux._levels)
        if self._imputer is not None:
            st = self._imputer._get_state()
            state["imp_cols"] = st["fill_cols"]
            state["imp_vals"] = st["fill_vals"]
            state["imp_in"] = np.asarray(self._imputer.input_cols, dtype=object)
        return state

    def _set_state(self, s):
        from .value_indexer import ValueIndexerModel
        self._plans = []
        kinds = [str(k) for k in s["plan_kinds"]]
        for i, (c, kind, dim) in enumerate(zip(s["plan_cols"], kinds,
                                               s["plan_dims"])):
            c = str(c)
            if kind in ("onehot", "index"):
                vim = ValueIndexerModel(input_col=c, output_col=f"__{c}_idx")
                vim._levels = np.asarray(s[f"levels_{i}"])
                self._plans.append((c, kind, vim))
            elif kind in ("vector", "hash"):
                self._plans.append((c, kind, int(dim)))
            else:
                self._plans.append((c, kind, None))
        self._imputer = None
        if "imp_cols" in s:
            from .clean_missing import CleanMissingDataModel
            imp = CleanMissingDataModel(
                input_cols=[str(c) for c in np.asarray(s["imp_in"])])
            imp._set_state({"fill_cols": s["imp_cols"], "fill_vals": s["imp_vals"]})
            self._imputer = imp

    def _transform(self, t: Table) -> Table:
        if self._imputer is not None:
            t = self._imputer.transform(t)
        n = len(t)
        if self._dense:
            blocks = []
            for c, kind, aux in self._plans:
                arr = t[c]
                if kind == "vector":
                    blocks.append(np.asarray(arr, np.float32))
                elif kind == "numeric":
                    blocks.append(np.asarray(arr, np.float32)[:, None])
                elif kind == "index":
                    idx = np.asarray(aux.transform(t)[aux.output_col], np.float32)
                    blocks.append(idx[:, None])
                elif kind == "onehot":
                    idx = np.asarray(aux.transform(t)[aux.output_col])
                    k = len(aux._levels)
                    oh = np.zeros((len(idx), k), np.float32)
                    valid = idx >= 0
                    oh[np.nonzero(valid)[0], idx[valid]] = 1.0
                    blocks.append(oh)
                elif kind == "hash":
                    nf = aux
                    h = hash_strings(arr.astype(str), num_bits=int(np.log2(nf)))
                    hot = np.zeros((len(h), nf), np.float32)
                    hot[np.arange(len(h)), h] = 1.0
                    blocks.append(hot)
            feats = (np.concatenate(blocks, axis=1) if blocks
                     else np.zeros((n, 0), np.float32))
            return t.with_column(self.output_col, feats)

        # sparse pair output: one (idx, val) slot column group per plan,
        # offset into the concatenated logical feature space — O(n * slots)
        # memory regardless of num_features (2^18 hashing never materializes)
        idx_parts, val_parts = [], []
        offset = 0
        for (c, kind, aux), width in zip(self._plans, self._plan_widths()):
            arr = t[c]
            if kind == "vector":
                idx_parts.append(np.broadcast_to(
                    offset + np.arange(width, dtype=np.int32), (n, width)))
                val_parts.append(np.asarray(arr, np.float32))
            elif kind == "numeric":
                idx_parts.append(np.full((n, 1), offset, np.int32))
                val_parts.append(np.asarray(arr, np.float32)[:, None])
            elif kind == "index":
                ix = np.asarray(aux.transform(t)[aux.output_col], np.float32)
                idx_parts.append(np.full((n, 1), offset, np.int32))
                val_parts.append(ix[:, None])
            elif kind == "onehot":
                ix = np.asarray(aux.transform(t)[aux.output_col])
                valid = ix >= 0
                idx_parts.append((offset + np.clip(ix, 0, width - 1))
                                 .astype(np.int32)[:, None])
                val_parts.append(valid.astype(np.float32)[:, None])
            elif kind == "hash":
                h = hash_strings(arr.astype(str),
                                 num_bits=int(np.log2(aux)))
                idx_parts.append((offset + h).astype(np.int32)[:, None])
                val_parts.append(np.ones((n, 1), np.float32))
            offset += width
        o = self.output_col
        out = t.with_columns({
            f"{o}_idx": np.concatenate(idx_parts, axis=1) if idx_parts
            else np.zeros((n, 0), np.int32),
            f"{o}_val": np.concatenate(val_parts, axis=1) if val_parts
            else np.zeros((n, 0), np.float32)})
        # consumers (linear models, to_dense) read the logical feature-space
        # width from column metadata instead of guessing from observed ids
        return out.with_column_meta(f"{o}_idx",
                                    logical_width=self.num_output_features)


class CountSelector(Estimator):
    """Drop all-zero feature slots (reference: featurize/CountSelector.scala)."""
    input_col = Param("input_col", "features column", "features")
    output_col = Param("output_col", "output column", "features")

    def _fit(self, t: Table) -> "CountSelectorModel":
        x = np.asarray(t[self.input_col])
        keep = np.abs(x).sum(axis=0) > 0
        m = CountSelectorModel(input_col=self.input_col, output_col=self.output_col)
        m._keep = keep
        return m


class CountSelectorModel(Model):
    input_col = Param("input_col", "features column", "features")
    output_col = Param("output_col", "output column", "features")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._keep = None

    def _get_state(self):
        return {"keep": np.asarray(self._keep)}

    def _set_state(self, s):
        self._keep = np.asarray(s["keep"])

    def _transform(self, t: Table) -> Table:
        x = np.asarray(t[self.input_col])
        return t.with_column(self.output_col, x[:, self._keep])


class DataConversion(Transformer):
    """Cast columns to a target dtype (reference: featurize/DataConversion.scala)."""
    cols = Param("cols", "columns to convert", None)
    convert_to = Param("convert_to", "numpy dtype name", "float32")

    def _transform(self, t: Table) -> Table:
        for c in self.cols or []:
            t = t.with_column(c, np.asarray(t[c]).astype(self.convert_to))
        return t
