"""ValueIndexer / IndexToValue: categorical <-> index codecs
(reference: featurize/ValueIndexer.scala, IndexToValue.scala; categorical
metadata semantics from core/schema/Categoricals.scala).
"""
from __future__ import annotations

import numpy as np

from ..core import (Estimator, Model, Param, Table, HasInputCol, HasOutputCol)
from ..ops.levels import lookup_levels


class ValueIndexer(Estimator, HasInputCol, HasOutputCol):
    """Fit the distinct levels of a column; transform values to int indices.
    Unseen values at transform time map to -1 (caller decides policy)."""

    def _fit(self, t: Table) -> "ValueIndexerModel":
        col = t[self.input_col]
        levels = np.unique(col[~_is_missing(col)])
        m = ValueIndexerModel(input_col=self.input_col,
                              output_col=self.output_col)
        m._levels = levels
        return m


class ValueIndexerModel(Model, HasInputCol, HasOutputCol):
    def __init__(self, **kw):
        super().__init__(**kw)
        self._levels = None

    def _get_state(self):
        return {"levels": np.asarray(self._levels)}

    def _set_state(self, s):
        self._levels = np.asarray(s["levels"])

    @property
    def levels(self):
        return self._levels

    def _transform(self, t: Table) -> Table:
        col = t[self.input_col]
        idx, found = lookup_levels(self._levels, col)
        out = np.where(found & ~_is_missing(col), idx, -1).astype(np.int64)
        # stamp categorical metadata so downstream stages can recover the
        # level names (core/schema/Categoricals.scala's CategoricalColumnInfo)
        return (t.with_column(self.output_col, out)
                 .with_column_meta(self.output_col,
                                   categorical_levels=self._levels.tolist()))


class IndexToValue(Model, HasInputCol, HasOutputCol):
    """Inverse mapping, given a fitted ValueIndexerModel's levels."""

    def __init__(self, levels=None, **kw):
        super().__init__(**kw)
        self._levels = None if levels is None else np.asarray(levels)

    def _get_state(self):
        return {"levels": np.asarray(self._levels)}

    def _set_state(self, s):
        self._levels = np.asarray(s["levels"])

    def _transform(self, t: Table) -> Table:
        idx = np.asarray(t[self.input_col]).astype(int)
        return t.with_column(self.output_col, self._levels[np.clip(idx, 0, None)])


def _is_missing(col: np.ndarray) -> np.ndarray:
    if np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    if col.dtype == object:
        return np.asarray([v is None for v in col])
    return np.zeros(len(col), dtype=bool)
