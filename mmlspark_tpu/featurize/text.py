"""TextFeaturizer: tokenize -> n-grams -> hashed TF -> IDF in one estimator
(reference: featurize/text/TextFeaturizer.scala builds the same SparkML
pipeline). Hashing uses murmur3 (ops/hashing); TF/IDF vectors are dense f32
rows sized 2^num_bits, ready for the device.
"""
from __future__ import annotations

import re

import numpy as np

from ..core import Estimator, Model, Param, Table, HasInputCol, HasOutputCol
from ..ops.hashing import hash_token

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _tokenize(text: str, to_lower=True):
    s = str(text)
    if to_lower:
        s = s.lower()
    return _TOKEN_RE.findall(s)


def _ngrams(tokens, n):
    if n <= 1:
        return list(tokens)
    out = list(tokens)
    for k in range(2, n + 1):
        out.extend("_".join(tokens[i:i + k]) for i in range(len(tokens) - k + 1))
    return out


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    use_tokenizer = Param("use_tokenizer", "regex-tokenize input", True)
    to_lower_case = Param("to_lower_case", "lowercase before tokenizing", True)
    use_ngram = Param("use_ngram", "add n-grams up to n_gram_length", False)
    n_gram_length = Param("n_gram_length", "max n-gram size", 2)
    num_features = Param("num_features", "hash slots (power of two)", 1 << 18)
    use_idf = Param("use_idf", "apply inverse document frequency", True)
    min_doc_freq = Param("min_doc_freq", "min docs for a slot to keep idf", 1)

    def _slots(self, texts):
        bits = int(np.log2(self.num_features))
        mask = (1 << bits) - 1
        rows = []
        for s in texts:
            toks = _tokenize(s, self.to_lower_case) if self.use_tokenizer else str(s).split()
            if self.use_ngram:
                toks = _ngrams(toks, self.n_gram_length)
            rows.append(np.asarray([hash_token(t) & mask for t in toks], np.int64))
        return rows

    def _fit(self, t: Table) -> "TextFeaturizerModel":
        rows = self._slots(t[self.input_col])
        nf = self.num_features
        idf = np.ones(nf, np.float32)
        if self.use_idf:
            df = np.zeros(nf, np.int64)
            for r in rows:
                df[np.unique(r)] += 1
            n_docs = len(rows)
            with np.errstate(divide="ignore"):
                idf = np.log((n_docs + 1.0) / (df + 1.0)).astype(np.float32)
            idf[df < self.min_doc_freq] = 0.0
        m = TextFeaturizerModel(**{k: v for k, v in self._paramMap.items()})
        m._idf = idf
        return m


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    use_tokenizer = Param("use_tokenizer", "regex-tokenize input", True)
    to_lower_case = Param("to_lower_case", "lowercase before tokenizing", True)
    use_ngram = Param("use_ngram", "add n-grams up to n_gram_length", False)
    n_gram_length = Param("n_gram_length", "max n-gram size", 2)
    num_features = Param("num_features", "hash slots (power of two)", 1 << 18)
    use_idf = Param("use_idf", "apply inverse document frequency", True)
    min_doc_freq = Param("min_doc_freq", "min docs for a slot to keep idf", 1)

    def __init__(self, **kw):
        super().__init__(**kw)
        self._idf = None

    def _get_state(self):
        return {"idf": self._idf}

    def _set_state(self, s):
        self._idf = np.asarray(s["idf"])

    def _transform(self, t: Table) -> Table:
        nf = self.num_features
        bits = int(np.log2(nf))
        mask = (1 << bits) - 1
        out = np.zeros((len(t), nf), np.float32)
        for i, s in enumerate(t[self.input_col]):
            toks = _tokenize(s, self.to_lower_case) if self.use_tokenizer else str(s).split()
            if self.use_ngram:
                toks = _ngrams(toks, self.n_gram_length)
            for tok in toks:
                out[i, hash_token(tok) & mask] += 1.0
        if self.use_idf and self._idf is not None:
            out *= self._idf[None, :]
        return t.with_column(self.output_col, out)
