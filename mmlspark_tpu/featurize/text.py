"""TextFeaturizer: tokenize -> n-grams -> hashed TF -> IDF in one estimator
(reference: featurize/text/TextFeaturizer.scala builds the same SparkML
pipeline). Hashing uses murmur3 (ops/hashing).

Output layout: at the reference's 2^18 default a dense (n, 2^18) TF matrix
is 1 MB/row — the sparse pair convention (ops/sparse.py) stores the same
information as `<out>_idx`/`<out>_val` (n, max_tokens) instead. Dense output
remains available (dense_output=True, or 'auto' under 2^14 slots) for
consumers that want a matrix.
"""
from __future__ import annotations

import re

import numpy as np

from ..core import Estimator, Model, Param, Table, HasInputCol, HasOutputCol
from ..core.params import one_of
from ..ops.hashing import hash_token
from ..ops.sparse import DENSE_AUTO_LIMIT, rows_to_pair

_TOKEN_RE = re.compile(r"[a-z0-9]+")


class _TokenHashCache:
    """Vectorized token hashing: murmur each UNIQUE token once per batch
    (replaces the per-row per-token hot loop)."""

    def __init__(self, mask: int):
        self.mask = mask
        self.cache: dict = {}

    def __call__(self, tokens):
        out = np.empty(len(tokens), np.int64)
        for i, tok in enumerate(tokens):
            h = self.cache.get(tok)
            if h is None:
                h = hash_token(tok) & self.mask
                self.cache[tok] = h
            out[i] = h
        return out


def _tokenize(text: str, to_lower=True):
    s = str(text)
    if to_lower:
        s = s.lower()
    return _TOKEN_RE.findall(s)


def _ngrams(tokens, n):
    if n <= 1:
        return list(tokens)
    out = list(tokens)
    for k in range(2, n + 1):
        out.extend("_".join(tokens[i:i + k]) for i in range(len(tokens) - k + 1))
    return out


class TextFeaturizer(Estimator, HasInputCol, HasOutputCol):
    use_tokenizer = Param("use_tokenizer", "regex-tokenize input", True)
    to_lower_case = Param("to_lower_case", "lowercase before tokenizing", True)
    use_ngram = Param("use_ngram", "add n-grams up to n_gram_length", False)
    n_gram_length = Param("n_gram_length", "max n-gram size", 2)
    num_features = Param("num_features", "hash slots (power of two)", 1 << 18)
    use_idf = Param("use_idf", "apply inverse document frequency", True)
    min_doc_freq = Param("min_doc_freq", "min docs for a slot to keep idf", 1)
    dense_output = Param("dense_output", "auto | True | False", "auto",
                         validator=one_of("auto", True, False))

    def _slots(self, texts):
        mask = self.num_features - 1
        hasher = _TokenHashCache(mask)
        rows = []
        for s in texts:
            toks = _tokenize(s, self.to_lower_case) if self.use_tokenizer else str(s).split()
            if self.use_ngram:
                toks = _ngrams(toks, self.n_gram_length)
            rows.append(hasher(toks))
        return rows

    def _fit(self, t: Table) -> "TextFeaturizerModel":
        rows = self._slots(t[self.input_col])
        nf = self.num_features
        idf = np.ones(nf, np.float32)
        if self.use_idf:
            df = np.zeros(nf, np.int64)
            for r in rows:
                df[np.unique(r)] += 1
            n_docs = len(rows)
            with np.errstate(divide="ignore"):
                idf = np.log((n_docs + 1.0) / (df + 1.0)).astype(np.float32)
            idf[df < self.min_doc_freq] = 0.0
        m = TextFeaturizerModel(**{k: v for k, v in self._paramMap.items()})
        m._idf = idf
        return m


class TextFeaturizerModel(Model, HasInputCol, HasOutputCol):
    use_tokenizer = Param("use_tokenizer", "regex-tokenize input", True)
    to_lower_case = Param("to_lower_case", "lowercase before tokenizing", True)
    use_ngram = Param("use_ngram", "add n-grams up to n_gram_length", False)
    n_gram_length = Param("n_gram_length", "max n-gram size", 2)
    num_features = Param("num_features", "hash slots (power of two)", 1 << 18)
    use_idf = Param("use_idf", "apply inverse document frequency", True)
    min_doc_freq = Param("min_doc_freq", "min docs for a slot to keep idf", 1)
    dense_output = Param("dense_output", "auto | True | False", "auto",
                         validator=one_of("auto", True, False))

    def __init__(self, **kw):
        super().__init__(**kw)
        self._idf = None

    def _get_state(self):
        return {"idf": self._idf}

    def _set_state(self, s):
        self._idf = np.asarray(s["idf"])

    @property
    def _dense(self) -> bool:
        d = self.dense_output
        return d is True or (d == "auto" and self.num_features <= DENSE_AUTO_LIMIT)

    def _transform(self, t: Table) -> Table:
        nf = self.num_features
        mask = nf - 1
        hasher = _TokenHashCache(mask)
        rows_idx, rows_val = [], []
        for s in t[self.input_col]:
            toks = _tokenize(s, self.to_lower_case) if self.use_tokenizer else str(s).split()
            if self.use_ngram:
                toks = _ngrams(toks, self.n_gram_length)
            slots, counts = np.unique(hasher(toks), return_counts=True)
            tf = counts.astype(np.float32)
            if self.use_idf and self._idf is not None:
                tf = tf * self._idf[slots]
            rows_idx.append(slots)
            rows_val.append(tf)
        idx, val = rows_to_pair(rows_idx, rows_val)
        if self._dense:
            from ..ops.sparse import to_dense
            return t.with_column(self.output_col, to_dense(idx, val, nf))
        return (t.with_columns({f"{self.output_col}_idx": idx,
                                f"{self.output_col}_val": val})
                 .with_column_meta(f"{self.output_col}_idx",
                                   logical_width=nf))
