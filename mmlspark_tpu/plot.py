"""Plotting helpers (reference: mmlspark/plot/plot.py — confusionMatrix and
roc over scored frames). Figures are matplotlib, gated behind lazy imports;
metric math comes from train/metrics so the plots agree with the evaluators.
Each helper takes a Table (or anything with [col]) and returns the Axes so
callers can compose/export.
"""
from __future__ import annotations

import numpy as np

from .train import metrics as _metrics


def confusion_matrix(t, y_col: str, y_hat_col: str, labels=None, ax=None):
    """Normalized confusion-matrix heatmap with counts overlaid
    (reference: plot.confusionMatrix)."""
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    y = np.asarray(t[y_col])
    y_hat = np.asarray(t[y_hat_col])
    if labels is None:
        labels = np.unique(np.concatenate([y, y_hat]))
    lab_ix = {v: i for i, v in enumerate(labels)}
    cm = np.zeros((len(labels), len(labels)), np.int64)
    for yt, yp in zip(y, y_hat):
        # values outside an explicit label list are excluded from the matrix
        # (sklearn confusion_matrix(labels=...) semantics); accuracy below
        # still covers every row
        if yt in lab_ix and yp in lab_ix:
            cm[lab_ix[yt], lab_ix[yp]] += 1
    with np.errstate(invalid="ignore"):
        cmn = cm / np.maximum(cm.sum(axis=1, keepdims=True), 1)
    accuracy = float((y == y_hat).mean())

    if ax is None:
        _, ax = plt.subplots()
    ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    ax.set_xticks(range(len(labels)), [str(v) for v in labels])
    ax.set_yticks(range(len(labels)), [str(v) for v in labels])
    for i in range(len(labels)):
        for j in range(len(labels)):
            ax.text(j, i, str(cm[i, j]), ha="center",
                    color="white" if cmn[i, j] > 0.5 else "black")
    ax.set_xlabel("Predicted Label")
    ax.set_ylabel("True Label")
    ax.set_title(f"Accuracy = {accuracy * 100:.1f}%")
    return ax


def roc(t, y_col: str, score_col: str, thresh: float = 0.5, ax=None):
    """ROC curve (reference: plot.roc); AUC from train.metrics so the figure
    matches ComputeModelStatistics."""
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    y = (np.asarray(t[y_col], np.float64) > thresh).astype(np.float64)
    s = np.asarray(t[score_col], np.float64)
    order = np.argsort(-s)
    ys = y[order]
    tps = np.cumsum(ys)
    fps = np.cumsum(1 - ys)
    tpr = np.concatenate([[0.0], tps / max(ys.sum(), 1)])
    fpr = np.concatenate([[0.0], fps / max((1 - ys).sum(), 1)])
    auc = _metrics.auc(y, s)

    if ax is None:
        _, ax = plt.subplots()
    ax.plot(fpr, tpr, label=f"AUC = {auc:.3f}")
    ax.plot([0, 1], [0, 1], linestyle="--", linewidth=0.8)
    ax.set_xlabel("False Positive Rate")
    ax.set_ylabel("True Positive Rate")
    ax.legend()
    return ax
