"""SummarizeData: per-column summary statistics as a Table (reference:
stages/SummarizeData.scala:20-238). Output schema matches the reference's
field lists (SummarizeData.scala:197-237): a 'Feature' row per input column
plus Count/Basic/Sample/Percentile blocks gated by the boolean params.
Quantiles are exact (np.quantile) — error_threshold exists for API parity;
0 means exact in the reference too (SummarizeData.scala:70-73).
"""
from __future__ import annotations

import numpy as np

from ..core import Param, Table, Transformer
from ..core.params import in_range

COUNT_FIELDS = ["Count", "Unique_Value_Count", "Missing_Value_Count"]
BASIC_FIELDS = ["Min", "1st_Quartile", "Median", "3rd_Quartile", "Max"]
SAMPLE_FIELDS = ["Sample_Variance", "Sample_Standard_Deviation",
                 "Sample_Skewness", "Sample_Kurtosis"]
PERCENTILE_QUANTILES = [0.005, 0.01, 0.05, 0.95, 0.99, 0.995]
PERCENTILE_FIELDS = ["P0_5", "P1", "P5", "P95", "P99", "P99_5"]


def _is_numeric(col: np.ndarray) -> bool:
    return col.ndim == 1 and np.issubdtype(col.dtype, np.number)


def _missing_mask(col: np.ndarray) -> np.ndarray:
    if np.issubdtype(col.dtype, np.floating):
        return np.isnan(col)
    if col.dtype == object:
        return np.array([v is None or (isinstance(v, float) and np.isnan(v))
                         for v in col])
    return np.zeros(col.shape[0], dtype=bool)


class SummarizeData(Transformer):
    """Compute count/basic/sample/percentile statistics for every column.

    Sample skewness/kurtosis use the population-moment definitions Spark's
    `skewness`/`kurtosis` aggregates use (m3/m2^1.5 and m4/m2^2 - 3);
    variance/std are the n-1 sample forms, matching `variance`/`stddev`
    (SummarizeData.scala:152-160).
    """
    counts = Param("counts", "compute count statistics", True)
    basic = Param("basic", "compute basic statistics", True)
    sample = Param("sample", "compute sample statistics", True)
    percentiles = Param("percentiles", "compute percentiles", True)
    error_threshold = Param("error_threshold",
                            "quantile error threshold - 0 is exact", 0.0,
                            validator=in_range(0.0))

    def _transform(self, t: Table) -> Table:
        fields = ["Feature"]
        if self.counts:
            fields += COUNT_FIELDS
        if self.basic:
            fields += BASIC_FIELDS
        if self.sample:
            fields += SAMPLE_FIELDS
        if self.percentiles:
            fields += PERCENTILE_FIELDS

        rows: dict[str, list] = {f: [] for f in fields}
        for name in t.columns:
            col = np.asarray(t[name])
            rows["Feature"].append(name)
            # vector columns (ndim > 1): like Spark's NumericType filter,
            # numeric stats are NaN (computeOnNumeric), counts treat each
            # row-vector as one value (computeOnAll)
            numeric = _is_numeric(col)
            missing = _missing_mask(col) if col.ndim == 1 else \
                np.zeros(col.shape[0], dtype=bool)
            valid = col[~missing] if missing.any() else col
            if self.counts:
                n_missing = float(missing.sum())
                rows["Count"].append(float(col.shape[0]) - n_missing)
                rows["Unique_Value_Count"].append(
                    float(len(np.unique(valid.reshape(valid.shape[0], -1)
                                        if valid.ndim > 1 else valid,
                                        axis=0 if valid.ndim > 1 else None)))
                    if col.shape[0] else 0.0)
                rows["Missing_Value_Count"].append(n_missing)
            stats = self._numeric_stats(valid.astype(np.float64)) \
                if numeric and valid.shape[0] else {}
            if self.basic:
                for f in BASIC_FIELDS:
                    rows[f].append(stats.get(f, np.nan))
            if self.sample:
                for f in SAMPLE_FIELDS:
                    rows[f].append(stats.get(f, np.nan))
            if self.percentiles:
                for f in PERCENTILE_FIELDS:
                    rows[f].append(stats.get(f, np.nan))

        data = {"Feature": np.asarray(rows["Feature"], dtype=object)}
        for f in fields[1:]:
            data[f] = np.asarray(rows[f], dtype=np.float64)
        return Table(data, t.npartitions)

    def _numeric_stats(self, v: np.ndarray) -> dict:
        out = {}
        n = v.shape[0]
        if self.basic:
            q = np.quantile(v, [0.0, 0.25, 0.5, 0.75, 1.0])
            out.update(zip(BASIC_FIELDS, q))
        if self.sample:
            mean = v.mean()
            d = v - mean
            m2 = float((d ** 2).mean())
            var = float(v.var(ddof=1)) if n > 1 else np.nan
            out["Sample_Variance"] = var
            out["Sample_Standard_Deviation"] = float(np.sqrt(var)) if n > 1 else np.nan
            if m2 > 0:
                out["Sample_Skewness"] = float((d ** 3).mean() / m2 ** 1.5)
                out["Sample_Kurtosis"] = float((d ** 4).mean() / m2 ** 2 - 3.0)
            else:
                out["Sample_Skewness"] = np.nan
                out["Sample_Kurtosis"] = np.nan
        if self.percentiles:
            q = np.quantile(v, PERCENTILE_QUANTILES)
            out.update(zip(PERCENTILE_FIELDS, q))
        return out
