"""Mini-batching transformers (reference: stages/MiniBatchTransformer.scala:16-225,
Batchers.scala:1-152): rows -> batch rows whose columns hold stacked arrays,
and the FlattenBatch inverse. Batching is what turns row streams into
MXU-shaped work for deep-net inference (CNTKModel batches with
FixedMiniBatchTransformer by default, cntk/CNTKModel.scala:377) and what
bounds latency for serving (DynamicMiniBatchTransformer drains whatever is
available up to a max).
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..core import Param, Table, Transformer
from ..core.params import in_range


def _stack_rows(col: np.ndarray, bounds) -> np.ndarray:
    out = np.empty(len(bounds), dtype=object)
    for i, (lo, hi) in enumerate(bounds):
        out[i] = col[lo:hi]
    return out


def shape_bucket(n: int, max_bucket: int = 1 << 20) -> int:
    """Smallest power-of-two >= n (capped): the row-count bucket jitted
    stages compile against. Padding request batches to these buckets keeps
    the number of distinct compiled shapes logarithmic in max batch size —
    the serving plan cache (io/plan.py) keys compiled transforms on it."""
    if n < 1:
        return 1
    return min(1 << (n - 1).bit_length(), max_bucket)


def pad_rows_to_bucket(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Pad a row-major array to `bucket` rows by repeating the final row.
    Repeating real data (not zeros) keeps padding inside the numeric range
    every row-wise stage already handles — no log(0)/divide-by-zero
    surprises from synthetic rows. Callers slice outputs back to the true
    row count."""
    n = arr.shape[0]
    if n >= bucket:
        return arr
    pad = np.broadcast_to(arr[-1:], (bucket - n,) + arr.shape[1:])
    return np.concatenate([arr, pad], axis=0)


class _BatcherBase(Transformer):
    def _bounds(self, n: int) -> list:
        raise NotImplementedError

    def _transform(self, t: Table) -> Table:
        bounds = self._bounds(len(t))
        return Table({name: _stack_rows(np.asarray(t[name]), bounds)
                      for name in t.columns}, t.npartitions)


class FixedMiniBatchTransformer(_BatcherBase):
    """Fixed-size batches (reference: FixedMiniBatchTransformer; buffered
    producer-thread mode is meaningless on a columnar Table and is omitted).

    `pad_last_batch=True` pads the trailing ragged batch to the full
    batch_size by repeating its final row — every batch then has one shape,
    so a jitted downstream stage compiles exactly once (the same
    shape-stability contract the serving plan cache enforces with
    `shape_bucket`)."""
    batch_size = Param("batch_size", "rows per batch", 10,
                       validator=in_range(1))
    pad_last_batch = Param("pad_last_batch",
                           "pad the ragged final batch to batch_size by "
                           "repeating its last row (shape-stable batches "
                           "for jitted stages)", False)

    def _bounds(self, n: int) -> list:
        b = self.batch_size
        return [(i, min(i + b, n)) for i in range(0, n, b)]

    def _transform(self, t: Table) -> Table:
        out = super()._transform(t)
        if not self.pad_last_batch:
            return out
        data = {}
        for name in out.columns:
            col = out[name]
            if len(col) and col[-1].shape[0] < self.batch_size:
                col = col.copy()
                col[-1] = pad_rows_to_bucket(col[-1], self.batch_size)
            data[name] = col
        return Table(data, out.npartitions)


class DynamicMiniBatchTransformer(_BatcherBase):
    """Drain-available batching (reference: DynamicMiniBatchTransformer):
    over a static Table all rows are 'available', so this equals one batch
    capped at max_batch_size — the latency-adaptive behavior lives in the
    serving path (ServingQuery.max_batch)."""
    max_batch_size = Param("max_batch_size", "max rows per batch", 1 << 30)

    def _bounds(self, n: int) -> list:
        b = min(self.max_batch_size, max(n, 1))
        return [(i, min(i + b, n)) for i in range(0, n, b)]


class TimeIntervalMiniBatchTransformer(_BatcherBase):
    """Batch rows arriving within a time window (reference:
    TimeIntervalMiniBatchTransformer). A static Table carries no arrival
    times unless a `timestamp_col` provides them; rows are then grouped into
    `interval_ms` windows."""
    interval_ms = Param("interval_ms", "window length in ms", 1000)
    timestamp_col = Param("timestamp_col", "epoch-seconds column (float)", None)
    max_batch_size = Param("max_batch_size", "cap per batch", 1 << 30)

    def _transform(self, t: Table) -> Table:
        if self.timestamp_col is None or self.timestamp_col not in t:
            return DynamicMiniBatchTransformer(
                max_batch_size=self.max_batch_size).transform(t)
        ts = np.asarray(t[self.timestamp_col], np.float64)
        window = np.floor((ts - ts.min()) / (self.interval_ms / 1000.0))
        bounds = []
        start = 0
        for i in range(1, len(ts) + 1):
            boundary = (i == len(ts) or window[i] != window[start]
                        or i - start >= self.max_batch_size)
            if boundary:
                bounds.append((start, i))
                start = i
        data = {name: _stack_rows(np.asarray(t[name]), bounds)
                for name in t.columns}
        return Table(data, t.npartitions)


class FlattenBatch(Transformer):
    """Inverse of the batchers (reference: FlattenBatch,
    MiniBatchTransformer.scala:16-42): object rows of stacked arrays ->
    plain rows again."""

    def _transform(self, t: Table) -> Table:
        out = {}
        for name in t.columns:
            col = t[name]
            if col.dtype == object and len(col) and isinstance(
                    col[0], np.ndarray):
                flat = np.concatenate([np.asarray(v) for v in col])
            else:
                flat = col
            out[name] = flat
        return Table(out, t.npartitions)
