"""Text-normalization stages (reference: stages/TextPreprocessor.scala:17-152,
stages/UnicodeNormalize.scala:20-79).

TextPreprocessor's reference implementation builds a char trie for
longest-match word replacement honoring word boundaries
(TextPreprocessor.scala:17-100). Here the same longest-match-at-word-boundary
semantics come from one compiled alternation regex sorted longest-first —
equivalent matching behavior, one vectorized pass per column.
"""
from __future__ import annotations

import re
import unicodedata
from typing import Optional

import numpy as np

from ..core import Param, Table, Transformer
from ..core.params import HasInputCol, HasOutputCol, one_of

_NORM_FUNCS = {
    "identity": lambda s: s,
    "lower": str.lower,
    "upper": str.upper,
}


class TextPreprocessor(Transformer, HasInputCol, HasOutputCol):
    """Find/replace words using a normalization function + longest-match map
    (reference: stages/TextPreprocessor.scala:103-152). `map` maps source
    strings to replacements; matching is longest-first and will not replace in
    the middle of an alphanumeric word (mapText's skipAlphas,
    TextPreprocessor.scala:73-84)."""
    map = Param("map", "string -> replacement map", None)
    norm_func = Param("norm_func", "normalization applied before matching",
                      "identity", validator=one_of(*_NORM_FUNCS))

    def __init__(self, map: Optional[dict] = None, **kw):
        super().__init__(**kw)
        if map is not None:
            self.set(map=dict(map))

    def _compiled(self):
        mapping = self.map or {}
        norm = _NORM_FUNCS[self.norm_func]
        normalized = {norm(k): v for k, v in mapping.items()}
        if not normalized:
            return None, normalized, norm
        keys = sorted(normalized, key=len, reverse=True)
        # \w guards on both sides = the trie's word-boundary semantics
        # (scan starts matches only at word starts; skipAlphas requires the
        # match to end at a non-alphanumeric boundary)
        pattern = re.compile(
            r"(?<![\w])(" + "|".join(re.escape(k) for k in keys) + r")(?![\w])")
        return pattern, normalized, norm

    def _transform(self, t: Table) -> Table:
        pattern, normalized, norm = self._compiled()
        col = t[self.input_col]

        def map_text(s):
            if s is None:
                return None
            s = norm(str(s))
            if pattern is None:
                return s
            return pattern.sub(lambda m: normalized[m.group(1)], s)

        out = np.array([map_text(v) for v in col], dtype=object)
        return t.with_column(self.output_col, out)


class UnicodeNormalize(Transformer, HasInputCol, HasOutputCol):
    """Unicode-normalize a string column (reference:
    stages/UnicodeNormalize.scala:20-79): NFC/NFD/NFKC/NFKD + optional
    lowercasing (default form NFKD, lower=True, matching getForm/getLower)."""
    form = Param("form", "normalization form", "NFKD",
                 validator=one_of("NFC", "NFD", "NFKC", "NFKD"))
    lower = Param("lower", "lowercase text first", True)

    def _transform(self, t: Table) -> Table:
        col = t[self.input_col]
        form = self.form

        def norm(s):
            if s is None:
                return None
            s = str(s)
            if self.lower:
                s = s.lower()
            return unicodedata.normalize(form, s)

        out = np.array([norm(v) for v in col], dtype=object)
        return t.with_column(self.output_col, out)
