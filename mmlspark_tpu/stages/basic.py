"""Utility column/row stages — the high-traffic half of the reference's stage zoo
(reference: stages/DropColumns.scala:65, SelectColumns.scala:67, RenameColumn.scala:46,
Repartition.scala:68, Cacher.scala:43, Explode.scala:43, UDFTransformer.scala:112,
Lambda.scala:65, StratifiedRepartition.scala:82).

Design notes (TPU-first): every stage is a whole-column transform over Table —
no per-row UDF loops. UDFTransformer is vectorized by default: the udf receives
the full column array(s) and returns a column, which keeps user code fusable
when it is jax/numpy. StratifiedRepartition spreads each label evenly over the
row order so every contiguous partition slice (partition-as-device) sees all labels —
the property LightGBM-style training needs (reference docstring,
StratifiedRepartition.scala:27-29).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from ..core import Param, Table, Transformer
from ..core.params import HasInputCol, HasOutputCol, HasLabelCol, HasSeed, one_of


class DropColumns(Transformer):
    """Drop the listed columns (reference: stages/DropColumns.scala:20-65;
    errors on absent columns like verifySchema does)."""
    cols = Param("cols", "columns to drop", None)

    def __init__(self, cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set(cols=list(cols))

    def _transform(self, t: Table) -> Table:
        missing = [c for c in (self.cols or []) if c not in t]
        if missing:
            raise KeyError(f"DropColumns: no such columns {missing}; have {t.columns}")
        return t.drop(*(self.cols or []))


class SelectColumns(Transformer):
    """Keep only the listed columns (reference: stages/SelectColumns.scala:22-67)."""
    cols = Param("cols", "columns to keep", None)

    def __init__(self, cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if cols is not None:
            self.set(cols=list(cols))

    def _transform(self, t: Table) -> Table:
        missing = [c for c in (self.cols or []) if c not in t]
        if missing:
            raise KeyError(f"SelectColumns: no such columns {missing}; have {t.columns}")
        return t.select(list(self.cols or []))


class RenameColumn(Transformer, HasInputCol, HasOutputCol):
    """Rename input_col to output_col (reference: stages/RenameColumn.scala:20-46)."""

    def _transform(self, t: Table) -> Table:
        return t.rename({self.input_col: self.output_col})


class Repartition(Transformer):
    """Change the Table's partition count (reference: stages/Repartition.scala:21-68).
    Partitions map to devices here, so this is the stage that re-grids work."""
    n = Param("n", "number of partitions", 1)
    disable = Param("disable", "pass through unchanged", False)

    def _transform(self, t: Table) -> Table:
        if self.disable:
            return t
        return t.repartition(self.n)


class Cacher(Transformer):
    """Materialization barrier (reference: stages/Cacher.scala:14-43). Columns
    here are already host-resident numpy, so caching is forcing any lazy
    device buffers back to host — a deliberate sync point."""
    disable = Param("disable", "pass through unchanged", False)

    def _transform(self, t: Table) -> Table:
        if self.disable:
            return t
        return t.materialize()


class Explode(Transformer, HasInputCol, HasOutputCol):
    """Explode an array-valued column into one row per element, repeating the
    other columns (reference: stages/Explode.scala:20-43)."""

    def _transform(self, t: Table) -> Table:
        col = t[self.input_col]
        if col.dtype == object:
            lengths = np.array([len(np.atleast_1d(v)) for v in col], dtype=np.int64)
            values = (np.concatenate([np.atleast_1d(v) for v in col])
                      if len(col) else np.empty(0))
        elif col.ndim >= 2:
            lengths = np.full(col.shape[0], col.shape[1], dtype=np.int64)
            values = col.reshape(-1, *col.shape[2:])
        else:
            raise TypeError(
                f"Explode: column {self.input_col!r} is scalar-valued "
                f"(dtype={col.dtype}, ndim={col.ndim}); need arrays per row")
        out = {}
        for name in t.columns:
            if name == self.input_col:
                continue
            out[name] = np.repeat(t[name], lengths, axis=0)
        out[self.output_col] = values
        return Table(out, t.npartitions)


class UDFTransformer(Transformer, HasOutputCol):
    """Apply a user function to one or more columns (reference:
    stages/UDFTransformer.scala:29-112). TPU-first: the udf is VECTORIZED by
    default — it receives whole column array(s) and returns a column, so
    numpy/jax udfs stay fused instead of running a per-row Python loop. Set
    vectorized=False for a scalar elementwise function."""
    input_col = Param("input_col", "single input column", None)
    input_cols = Param("input_cols", "multiple input columns", None)
    udf = Param("udf", "callable column(s) -> column (saved by qualified name; pickle is opt-in)", None)
    vectorized = Param("vectorized", "udf takes whole columns, not scalars", True)

    def _transform(self, t: Table) -> Table:
        fn = self.udf
        if fn is None:
            raise ValueError("UDFTransformer: udf param is not set")
        if self.input_cols:
            args = [t[c] for c in self.input_cols]
        else:
            args = [t[self.input_col or "input"]]
        if self.vectorized:
            # pass device arrays through untouched — with_column keeps jax
            # results on device; forcing numpy here would desync the lazy
            # device-column flow Table supports
            out = fn(*args)
        else:
            out = np.asarray([fn(*row) for row in zip(*args)])
        return t.with_column(self.output_col, out)


class Lambda(Transformer):
    """Arbitrary Table -> Table function as a pipeline stage (reference:
    stages/Lambda.scala:19-65)."""
    transform_fn = Param("transform_fn", "callable Table -> Table (saved by qualified name; pickle is opt-in)",
                         None)

    def __init__(self, transform_fn: Optional[Callable] = None, **kw):
        super().__init__(**kw)
        if transform_fn is not None:
            self.set(transform_fn=transform_fn)

    def _transform(self, t: Table) -> Table:
        fn = self.transform_fn
        if fn is None:
            raise ValueError("Lambda: transform_fn param is not set")
        out = fn(t)
        if not isinstance(out, Table):
            raise TypeError("Lambda transform_fn must return a Table")
        return out


class StratifiedRepartition(Transformer, HasLabelCol, HasSeed):
    """Reorder (and optionally resample) rows so every partition contains every
    label (reference: stages/StratifiedRepartition.scala:27-82). Needed when a
    distributed learner requires each device shard to see all classes.

    Modes (StratifiedRepartition.scala:53-77):
    - 'original': keep counts, just spread each label evenly over the row order.
    - 'equal': resample each label (with replacement) to max(count, npartitions)
      so labels are balanced, then spread.
    - 'mixed' (default): heuristic — upsample only labels below the mean share
      (total/n_labels) up to that share; labels at/above it keep their counts.
    """
    mode = Param("mode", "equal | original | mixed", "mixed",
                 validator=one_of("equal", "original", "mixed"))

    def _transform(self, t: Table) -> Table:
        labels = np.asarray(t[self.label_col])
        uniq, inv, counts = np.unique(labels, return_inverse=True,
                                      return_counts=True)
        rng = np.random.default_rng(self.seed)
        per_label = [np.flatnonzero(inv == k) for k in range(len(uniq))]

        if self.mode == "original":
            targets = counts
        elif self.mode == "equal":
            # equal share: every label resampled to the max count
            # (getEqualLabelCount, StratifiedRepartition.scala:74-77)
            targets = np.full_like(counts, max(int(counts.max()), t.npartitions))
        else:  # mixed: lift only under-represented labels to the mean share
            mean_share = max(int(np.ceil(counts.sum() / len(counts))),
                             t.npartitions)
            targets = np.maximum(counts, mean_share)

        sampled = []
        for idx, target in zip(per_label, targets):
            target = int(target)
            if target <= len(idx):
                sampled.append(idx[:target])
            else:
                extra = rng.choice(idx, size=target - len(idx), replace=True)
                sampled.append(np.concatenate([idx, extra]))

        # spread each label uniformly over [0,1) by fractional rank, then sort:
        # every contiguous partition slice gets a proportional share of every
        # label (round-robin compaction would front-load minority labels and
        # leave a majority-only tail)
        keys = np.concatenate([(np.arange(len(idx)) + 0.5) / len(idx)
                               for idx in sampled])
        flat = np.concatenate(sampled)[np.argsort(keys, kind="stable")]
        return Table({n: t[n][flat] for n in t.columns}, t.npartitions)
