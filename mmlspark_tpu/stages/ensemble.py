"""EnsembleByKey + MultiColumnAdapter + ClassBalancer (reference:
stages/EnsembleByKey.scala:22-208, MultiColumnAdapter.scala:18-135,
ClassBalancer.scala:17-101).

Group-bys are implemented with np.unique inverse indices + np.add.at
segment sums — the same segment-reduction shape the device kernels use, so
vector columns aggregate without materializing per-group Python lists.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core import Estimator, Model, Param, Table, Transformer
from ..core.params import HasInputCol, HasOutputCol, one_of
from ..core.pipeline import PipelineModel


def _group_ids(t: Table, keys: Sequence[str]):
    """Dense group ids + first-occurrence row per group for the key columns."""
    if len(keys) == 1:
        uniq, first, inv = np.unique(t[keys[0]], return_index=True,
                                     return_inverse=True)
        return inv, first, len(uniq)
    # vectorized compound key: per-key dense ids composed by mixed-radix
    # (inv = inv*base_k + inv_k) — collision-free, no per-row Python work
    combined = np.zeros(len(t), dtype=np.int64)
    for k in keys:
        uniq_k, inv_k = np.unique(t[k], return_inverse=True)
        combined = combined * len(uniq_k) + inv_k
    uniq, first, inv = np.unique(combined, return_index=True,
                                 return_inverse=True)
    return inv, first, len(uniq)


class EnsembleByKey(Transformer):
    """Average score columns within key groups (reference:
    stages/EnsembleByKey.scala:22-208). strategy='mean' is the only strategy
    the reference allows (EnsembleByKey.scala:56-58). collapse_group=True
    yields one row per group; False joins the group mean back onto each row
    (EnsembleByKey.scala:132-146)."""
    keys = Param("keys", "key columns to group by", None)
    cols = Param("cols", "columns to ensemble", None)
    col_names = Param("col_names", "output names per ensembled column", None)
    strategy = Param("strategy", "ensembling strategy", "mean",
                     validator=one_of("mean"))
    collapse_group = Param("collapse_group",
                           "collapse each group to a single row", True)

    def __init__(self, keys: Optional[Sequence[str]] = None,
                 cols: Optional[Sequence[str]] = None, **kw):
        super().__init__(**kw)
        if keys is not None:
            self.set(keys=list(keys))
        if cols is not None:
            self.set(cols=list(cols))

    def _transform(self, t: Table) -> Table:
        keys = list(self.keys or [])
        cols = list(self.cols or [])
        if not keys or not cols:
            raise ValueError("EnsembleByKey needs keys and cols")
        names = list(self.col_names) if self.col_names else \
            [f"{self.strategy}({c})" for c in cols]
        if len(names) != len(cols):
            raise ValueError(
                f"col_names ({len(names)}) must match cols ({len(cols)})")
        inv, first, n_groups = _group_ids(t, keys)
        counts = np.bincount(inv, minlength=n_groups).astype(np.float64)

        agg = {}
        for c, out_name in zip(cols, names):
            col = np.asarray(t[c], dtype=np.float64)
            if col.ndim == 1:
                sums = np.bincount(inv, weights=col, minlength=n_groups)
                agg[out_name] = sums / counts
            else:  # vector column: segment-sum each component
                sums = np.zeros((n_groups, col.shape[1]))
                np.add.at(sums, inv, col)
                agg[out_name] = sums / counts[:, None]

        if self.collapse_group:
            data = {k: t[k][first] for k in keys}
            data.update(agg)
            return Table(data, t.npartitions)
        return t.with_columns({name: vals[inv] for name, vals in agg.items()})


class MultiColumnAdapter(Estimator):
    """Fit one copy of base_stage per (input, output) column pair (reference:
    stages/MultiColumnAdapter.scala:18-135); the fitted result is a
    PipelineModel chaining the per-column models."""
    base_stage = Param("base_stage", "stage to replicate per column", None)
    input_cols = Param("input_cols", "input columns", None)
    output_cols = Param("output_cols", "output columns", None)

    def _per_column_stages(self):
        base = self.base_stage
        if base is None:
            raise ValueError("MultiColumnAdapter: base_stage is not set")
        ins, outs = list(self.input_cols or []), list(self.output_cols or [])
        if len(ins) != len(outs):
            raise ValueError(
                f"input_cols ({len(ins)}) and output_cols ({len(outs)}) "
                f"must pair up")  # MultiColumnAdapter.scala:62-66
        return [base.copy({"input_col": i, "output_col": o})
                for i, o in zip(ins, outs)]

    def _fit(self, t: Table) -> PipelineModel:
        fitted = []
        current = t
        for stage in self._per_column_stages():
            if isinstance(stage, Estimator):
                model = stage.fit(current)
            else:
                model = stage
            current = model.transform(current)
            fitted.append(model)
        return PipelineModel(stages=fitted)


class ClassBalancer(Estimator, HasInputCol, HasOutputCol):
    """Compute inverse-frequency sample weights per label value (reference:
    stages/ClassBalancer.scala:17-61): weight = max(count) / count."""
    input_col = Param("input_col", "label column", "label")
    output_col = Param("output_col", "weight column", "weight")
    broadcast_join = Param("broadcast_join",
                           "broadcast the weight map (API parity; the map is "
                           "always host-resident here)", True)

    def _fit(self, t: Table) -> "ClassBalancerModel":
        values, counts = np.unique(t[self.input_col], return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        return ClassBalancerModel(
            input_col=self.input_col, output_col=self.output_col,
            broadcast_join=self.broadcast_join,
            values=values, weights=weights)


class ClassBalancerModel(Model, HasInputCol, HasOutputCol):
    """Joins the label->weight map onto the input (reference:
    stages/ClassBalancer.scala:66-101)."""
    input_col = Param("input_col", "label column", "label")
    output_col = Param("output_col", "weight column", "weight")
    broadcast_join = Param("broadcast_join", "API parity flag", True)
    values = Param("values", "distinct label values", None)
    weights = Param("weights", "weight per distinct label value", None)

    def _transform(self, t: Table) -> Table:
        values, weights = self.values, self.weights
        if values is None:
            raise ValueError("ClassBalancerModel is not fitted")
        col = t[self.input_col]
        idx = np.searchsorted(values, col)
        idx = np.clip(idx, 0, len(np.asarray(values)) - 1)
        matched = np.asarray(values)[idx] == col
        w = np.where(matched, np.asarray(weights)[idx], np.nan)
        return t.with_column(self.output_col, w)
