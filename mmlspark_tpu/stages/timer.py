"""Timer: wrap any stage and log how long its fit/transform takes (reference:
stages/Timer.scala:20-133). The timing hook doubles as the framework's
light profiling stage — pair with utils.stopwatch for code-level timing and
jax.profiler (utils.tracing) for device traces.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

from ..core import Estimator, Model, Param, Table, Transformer
from ..core.pipeline import PipelineStage

_logger = logging.getLogger("mmlspark_tpu.timer")


class _TimerParams:
    log_to_console = Param("log_to_console",
                           "print timing lines (Timer.scala logToScala)", True)
    disable_materialization = Param(
        "disable_materialization",
        "when False, force host materialization before/after so the timing "
        "covers real work, not lazy views (Timer.scala:31-36)", True)


def _emit(stage, seconds: float, action: str, count, enabled: bool):
    amount = f" {count} rows" if count is not None else ""
    msg = f"{type(stage).__name__} took {seconds}s to {action}{amount}"
    _logger.info(msg)
    if enabled:
        print(msg)


class Timer(Estimator, _TimerParams):
    """Times the wrapped stage's fit (reference: Timer.scala:55-88); produces
    a TimerModel that times every transform."""
    stage = Param("stage", "inner stage to time", None)

    def __init__(self, stage: Optional[PipelineStage] = None, **kw):
        super().__init__(**kw)
        if stage is not None:
            self.set(stage=stage)

    def fit_with_time(self, t: Table):
        inner = self.stage
        if inner is None:
            raise ValueError("Timer: stage param is not set")
        count = None if self.disable_materialization else len(t.materialize())
        if isinstance(inner, Estimator):
            t0 = time.perf_counter()
            fitted = inner.fit(t)
            elapsed = time.perf_counter() - t0
            msg = f"{type(inner).__name__} fit in {elapsed}s"
            _emit(inner, elapsed, "fit", count, False)
        else:
            fitted, msg = inner, ""
        model = TimerModel(
            transformer=fitted, log_to_console=self.log_to_console,
            disable_materialization=self.disable_materialization)
        return model, msg

    def _fit(self, t: Table) -> "TimerModel":
        model, msg = self.fit_with_time(t)
        if msg and self.log_to_console:
            print(msg)
        return model


class TimerModel(Model, _TimerParams):
    """Times the wrapped transformer (reference: Timer.scala:90-133)."""
    transformer = Param("transformer", "inner transformer to time", None)

    def transform_with_time(self, t: Table):
        inner = self.transformer
        if inner is None:
            raise ValueError("TimerModel: transformer param is not set")
        before = t if self.disable_materialization else t.materialize()
        count = None if self.disable_materialization else len(before)
        t0 = time.perf_counter()
        out = inner.transform(before)
        if not self.disable_materialization:
            out = out.materialize()
        elapsed = time.perf_counter() - t0
        return out, f"{type(inner).__name__} took {elapsed}s to transform" + (
            f" {count} rows" if count is not None else "")

    def _transform(self, t: Table) -> Table:
        out, msg = self.transform_with_time(t)
        _logger.info(msg)
        if self.log_to_console:
            print(msg)
        return out
