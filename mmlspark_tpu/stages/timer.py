"""Timer: wrap any stage and log how long its fit/transform takes (reference:
stages/Timer.scala:20-133). The timing hook doubles as the framework's
light profiling stage — pair with utils.stopwatch for code-level timing and
jax.profiler (utils.tracing) for device traces.
"""
from __future__ import annotations

import logging
import time
from typing import Optional

from ..core import Estimator, Model, Param, Table, Transformer
from ..core.pipeline import PipelineStage
from ..telemetry.names import stage_span

_logger = logging.getLogger("mmlspark_tpu.timer")


class _TimerParams:
    log_to_console = Param("log_to_console",
                           "print timing lines (Timer.scala logToScala)", True)
    disable_materialization = Param(
        "disable_materialization",
        "when False, force host materialization before/after so the timing "
        "covers real work, not lazy views (Timer.scala:31-36)", True)
    telemetry = Param(
        "telemetry",
        "record fit/transform timings as telemetry tracer spans "
        "(stage.<Type>.<action>) instead of console prints — pipeline "
        "stage timings then land in the same span log as serving/training "
        "(docs/observability.md)", False)


def _observe_stage(stage, action: str, seconds: float) -> bool:
    """Telemetry sink for a stage timing: a completed span named
    `stage.<Type>.<action>` under the active trace (or its own). Returns
    whether the span was actually recorded — with sampling off the Timer
    must NOT silently drop a timing the user asked for, so the caller
    falls back to the console print."""
    from ..telemetry.spans import get_tracer
    return get_tracer().observe(stage_span(type(stage).__name__, action),
                                seconds) is not None


def _emit(stage, seconds: float, action: str, count, enabled: bool):
    amount = f" {count} rows" if count is not None else ""
    msg = f"{type(stage).__name__} took {seconds}s to {action}{amount}"
    _logger.info(msg)
    if enabled:
        print(msg)


class Timer(Estimator, _TimerParams):
    """Times the wrapped stage's fit (reference: Timer.scala:55-88); produces
    a TimerModel that times every transform."""
    stage = Param("stage", "inner stage to time", None)

    def __init__(self, stage: Optional[PipelineStage] = None, **kw):
        super().__init__(**kw)
        if stage is not None:
            self.set(stage=stage)

    def fit_with_time(self, t: Table):
        model, msg, _recorded = self._fit_timed(t)
        return model, msg

    def _fit_timed(self, t: Table):
        """(model, msg, span_recorded) — the flag is per-CALL, never stored
        on the shared stage (concurrent fits must not race each other's
        print-fallback decision)."""
        inner = self.stage
        if inner is None:
            raise ValueError("Timer: stage param is not set")
        count = None if self.disable_materialization else len(t.materialize())
        recorded = False
        if isinstance(inner, Estimator):
            t0 = time.perf_counter()
            fitted = inner.fit(t)
            elapsed = time.perf_counter() - t0
            msg = f"{type(inner).__name__} fit in {elapsed}s"
            _emit(inner, elapsed, "fit", count, False)
            recorded = (self.telemetry
                        and _observe_stage(inner, "fit", elapsed))
        else:
            fitted, msg = inner, ""
        model = TimerModel(
            transformer=fitted, log_to_console=self.log_to_console,
            disable_materialization=self.disable_materialization,
            telemetry=self.telemetry)
        return model, msg, recorded

    def _fit(self, t: Table) -> "TimerModel":
        model, msg, recorded = self._fit_timed(t)
        # telemetry mode: the console line is replaced ONLY when a span was
        # actually recorded — with sampling off, dropping both would lose
        # the timing the user asked for
        if msg and self.log_to_console and not recorded:
            print(msg)
        return model


class TimerModel(Model, _TimerParams):
    """Times the wrapped transformer (reference: Timer.scala:90-133)."""
    transformer = Param("transformer", "inner transformer to time", None)

    def transform_with_time(self, t: Table):
        out, msg, _recorded = self._transform_timed(t)
        return out, msg

    def _transform_timed(self, t: Table):
        """(out, msg, span_recorded) — per-call flag, see Timer._fit_timed
        (a shared TimerModel transformed by concurrent serving workers
        must not race the fallback decision through instance state)."""
        inner = self.transformer
        if inner is None:
            raise ValueError("TimerModel: transformer param is not set")
        before = t if self.disable_materialization else t.materialize()
        count = None if self.disable_materialization else len(before)
        t0 = time.perf_counter()
        out = inner.transform(before)
        if not self.disable_materialization:
            out = out.materialize()
        elapsed = time.perf_counter() - t0
        recorded = (self.telemetry
                    and _observe_stage(inner, "transform", elapsed))
        msg = f"{type(inner).__name__} took {elapsed}s to transform" + (
            f" {count} rows" if count is not None else "")
        return out, msg, recorded

    def _transform(self, t: Table) -> Table:
        out, msg, recorded = self._transform_timed(t)
        _logger.info(msg)
        if self.log_to_console and not recorded:
            print(msg)
        return out
