"""Utility pipeline stages (reference: stages/ — SURVEY.md §2.8)."""
from .batching import (DynamicMiniBatchTransformer, FixedMiniBatchTransformer,
                       FlattenBatch, TimeIntervalMiniBatchTransformer)

__all__ = ["DynamicMiniBatchTransformer", "FixedMiniBatchTransformer",
           "FlattenBatch", "TimeIntervalMiniBatchTransformer"]
