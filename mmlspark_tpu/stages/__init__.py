"""Utility pipeline stages (reference: stages/ — SURVEY.md §2.8)."""
from .basic import (Cacher, DropColumns, Explode, Lambda, RenameColumn,
                    Repartition, SelectColumns, StratifiedRepartition,
                    UDFTransformer)
from .batching import (DynamicMiniBatchTransformer, FixedMiniBatchTransformer,
                       FlattenBatch, TimeIntervalMiniBatchTransformer,
                       pad_rows_to_bucket, shape_bucket)
from .ensemble import (ClassBalancer, ClassBalancerModel, EnsembleByKey,
                       MultiColumnAdapter)
from .summarize import SummarizeData
from .text_stages import TextPreprocessor, UnicodeNormalize
from .timer import Timer, TimerModel

__all__ = [
    "Cacher", "ClassBalancer", "ClassBalancerModel", "DropColumns",
    "DynamicMiniBatchTransformer", "EnsembleByKey", "Explode",
    "FixedMiniBatchTransformer", "FlattenBatch", "Lambda",
    "MultiColumnAdapter", "RenameColumn", "Repartition", "SelectColumns",
    "StratifiedRepartition", "SummarizeData", "TextPreprocessor",
    "TimeIntervalMiniBatchTransformer", "Timer", "TimerModel",
    "UDFTransformer", "UnicodeNormalize", "pad_rows_to_bucket",
    "shape_bucket",
]
