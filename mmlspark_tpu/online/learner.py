"""Incremental VW learner on a fixed shape bucket (docs/online.md).

`OnlineLearner` carries the mutable training state the batch
`fit_vw` path deliberately hides: hashed weights, bias, and the
AdaGrad accumulator, updated one minibatch at a time. Every
`partial_fit` pads its rows to ONE canonical (rows, k) shape bucket,
so every update in the process's lifetime — warm-start, steady
stream, post-refit — hits the same compiled executable
(`online_update_contract` pins this; recompiles on the update path
are a bug, not a cost).

Padding follows the batch learner's convention exactly: padded pairs
carry `val == 0` (zero gradient contribution) and padded rows carry
`w == 0` (zero loss weight), so a padded minibatch computes the same
update as the ragged one.

Each `make_model()` is a content-addressed candidate: a normal
`VowpalWabbit*Model` stamped with online lineage, its `ModelVersion`
journaled to the run ledger when one is configured — the same record
shape batch fits stamp, so the deployment trail reads uniformly.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.vw.learner import (VWParams, _loss_grad, _predict_margin,
                                 _predict_sparse)
from ..reliability.metrics import reliability_metrics
from ..stages.batching import shape_bucket
from ..telemetry import names as tnames


@functools.partial(jax.jit, static_argnames=("loss_function",))
def _online_update(idx, val, y, w, weights, bias, acc, lr, l2,
                   loss_function="logistic"):
    """One AdaGrad minibatch update at a fixed (rows, k) shape.

    Mirrors `_fit_sgd`'s inner step but takes the accumulator as
    carried state instead of zero-initializing it — that is what makes
    the update *incremental* across refits."""
    dim = weights.shape[0]
    margin = _predict_margin(weights, bias, idx, val)
    gm, loss = _loss_grad(margin, y, w, loss_function)
    flat_idx = (idx & (dim - 1)).reshape(-1)
    flat_g = (gm[:, None] * val).reshape(-1)
    gw = jax.ops.segment_sum(flat_g, flat_idx, num_segments=dim)
    denom = jnp.maximum(jnp.sum(w), 1.0)
    gw = gw / denom + l2 * weights
    gb = jnp.sum(gm) / denom
    acc = acc + gw * gw
    weights = weights - lr * gw / jnp.sqrt(acc + 1e-8)
    bias = bias - lr * gb
    return weights, bias, acc, jnp.sum(loss) / denom




class OnlineLearner:
    """Incremental VW training state with snapshot/rewind.

    Parameters
    ----------
    params:      `VWParams` — `loss_function` picks the model family
                 (`logistic` -> classifier, `squared` -> regressor);
                 `learning_rate`/`l2`/`num_bits` apply per minibatch.
                 The online path is always adaptive (AdaGrad): that is
                 the mode whose accumulator makes warm-started
                 incremental updates well-behaved.
    warm_start:  incumbent `VowpalWabbit*Model` (or `(weights, bias)`)
                 whose weights seed the learner. The AdaGrad
                 accumulator starts at zero and is carried across every
                 subsequent refit.
    rows:        the fixed row bucket every minibatch is padded to.
    k:           the fixed pairs-per-row bucket; inferred (power of
                 two) from the first minibatch when None, frozen after.
    """

    MAX_K = 1024

    def __init__(self, params: Optional[VWParams] = None, *,
                 warm_start=None, rows: int = 256, k: Optional[int] = None,
                 metrics=None):
        self.params = params or VWParams(loss_function="logistic")
        self.rows = max(int(rows), 1)
        self._k = None if k is None else shape_bucket(int(k), self.MAX_K)
        self._metrics = metrics if metrics is not None \
            else reliability_metrics
        dim = 1 << self.params.num_bits
        weights, bias = np.zeros(dim, np.float32), 0.0
        if warm_start is not None:
            if hasattr(warm_start, "_weights"):
                weights = np.asarray(warm_start._weights, np.float32)
                bias = float(warm_start._bias)
            else:
                weights, bias = warm_start
                weights = np.asarray(weights, np.float32)
                bias = float(bias)
            if weights.shape[0] != dim:
                raise ValueError(
                    f"warm-start weights have {weights.shape[0]} slots, "
                    f"params.num_bits={self.params.num_bits} needs {dim}")
        self._weights = weights.copy()
        self._bias = np.float32(bias)
        self._acc = np.zeros(dim, np.float32)
        self.updates = 0        # compiled minibatch executions
        self.examples = 0       # live (unpadded) rows consumed
        self.refits = 0         # make_model() candidates produced
        self.last_loss: Optional[float] = None

    # -- shape discipline -----------------------------------------------------
    def _bucket(self, idx: np.ndarray, val: np.ndarray):
        """Freeze k on first contact, then pad pairs out to it. Padded
        pairs use idx 0 / val 0 — zero gradient, zero score."""
        if self._k is None:
            self._k = shape_bucket(max(idx.shape[1], 1), self.MAX_K)
        if idx.shape[1] > self._k:
            raise ValueError(
                f"minibatch has {idx.shape[1]} pairs/row; this learner's "
                f"frozen k bucket is {self._k}")
        pad = self._k - idx.shape[1]
        if pad:
            idx = np.pad(idx, ((0, 0), (0, pad)))
            val = np.pad(val, ((0, 0), (0, pad)))
        return idx, val

    @property
    def k(self) -> Optional[int]:
        return self._k

    # -- the update -----------------------------------------------------------
    def partial_fit(self, idx, val, y, w=None) -> dict:
        """Fold a ragged minibatch of hashed sparse pairs into the
        learner. Rows are chunked and padded to the fixed (rows, k)
        bucket; every chunk is one execution of the ONE compiled
        update."""
        idx = np.asarray(idx, np.int32)
        val = np.asarray(val, np.float32)
        y = np.asarray(y, np.float32).reshape(-1)
        if idx.ndim != 2 or idx.shape != val.shape:
            raise ValueError("idx/val must be matching (n, k) arrays")
        if idx.shape[0] != y.shape[0]:
            raise ValueError("idx/val and y row counts differ")
        w = (np.ones_like(y) if w is None
             else np.asarray(w, np.float32).reshape(-1))
        idx, val = self._bucket(idx, val)
        lr = np.float32(self.params.learning_rate)
        l2 = np.float32(self.params.l2)
        total_loss, chunks = 0.0, 0
        for start in range(0, idx.shape[0], self.rows):
            ci, cv = idx[start:start + self.rows], val[start:start + self.rows]
            cy, cw = y[start:start + self.rows], w[start:start + self.rows]
            live = ci.shape[0]
            if live < self.rows:
                pad = ((0, self.rows - live), (0, 0))
                ci, cv = np.pad(ci, pad), np.pad(cv, pad)
                cy = np.pad(cy, (0, self.rows - live))
                cw = np.pad(cw, (0, self.rows - live))   # w=0: no loss
            weights, bias, acc, loss = _online_update(
                ci, cv, cy, cw, self._weights, self._bias, self._acc,
                lr, l2, loss_function=self.params.loss_function)
            self._weights = np.asarray(weights)
            self._bias = np.float32(bias)
            self._acc = np.asarray(acc)
            total_loss += float(loss)
            chunks += 1
            self.updates += 1
            self.examples += int(live)
            self._metrics.inc(tnames.ONLINE_LEARNER_UPDATES)
        self.last_loss = total_loss / max(chunks, 1)
        return {"updates": chunks, "examples": int(y.shape[0]),
                "loss": self.last_loss}

    # -- snapshot / rewind ----------------------------------------------------
    def snapshot(self) -> dict:
        """Copy-out of everything a failed refit must rewind."""
        return {"weights": self._weights.copy(),
                "bias": np.float32(self._bias),
                "acc": self._acc.copy(),
                "updates": self.updates, "examples": self.examples,
                "refits": self.refits, "last_loss": self.last_loss}

    def restore(self, snap: dict) -> None:
        self._weights = snap["weights"].copy()
        self._bias = np.float32(snap["bias"])
        self._acc = snap["acc"].copy()
        self.updates = snap["updates"]
        self.examples = snap["examples"]
        self.refits = snap["refits"]
        self.last_loss = snap["last_loss"]

    # -- candidate production -------------------------------------------------
    def make_model(self, features_col: str = "features",
                   prediction_col: str = "prediction",
                   reference_profile: Optional[dict] = None,
                   reason: Optional[str] = None):
        """Freeze the current state into a content-addressed candidate.

        Returns a plain `VowpalWabbit*Model` (classification for
        logistic loss, regression for squared) stamped with online
        lineage; its `ModelVersion` is journaled to the run ledger when
        one is configured — same record shape as batch-fit stamps."""
        from ..models.vw.estimators import (VowpalWabbitClassificationModel,
                                            VowpalWabbitRegressionModel)
        stats = {"passes": 0, "online_updates": self.updates,
                 "online_examples": self.examples,
                 "final_loss": self.last_loss}
        kw = dict(weights=self._weights.copy(), bias=float(self._bias),
                  stats=stats, features_col=features_col,
                  prediction_col=prediction_col,
                  num_bits=self.params.num_bits)
        if self.params.loss_function == "logistic":
            model = VowpalWabbitClassificationModel(**kw)
        else:
            model = VowpalWabbitRegressionModel(**kw)
        self.refits += 1
        lineage = {"estimator": "OnlineLearner",
                   "loss_function": self.params.loss_function,
                   "refit": self.refits, "updates": self.updates,
                   "examples": self.examples, "loss": self.last_loss}
        if reason is not None:
            lineage["reason"] = reason
        model.lineage = lineage
        if reference_profile is not None:
            model.quality_profile = reference_profile
        from ..telemetry import lineage as tlineage
        ledger = tlineage.get_run_ledger()
        if ledger is not None:
            ledger.append(
                tlineage.model_version(model, content=True).export())
        return model


# --------------------------------------------------------------- contract
# PR-13 discipline: the semantic tier proves the claim the docstring
# makes — warm-start, steady-stream, and post-refit updates at the
# canonical bucket are ONE executable, with zero collectives (the online
# path is single-host by design; scale-out happens in batch refits).
from ..analysis.semantic import Case, hot_path_contract  # noqa: E402

_CONTRACT_ROWS, _CONTRACT_K, _CONTRACT_BITS = 32, 8, 12


@hot_path_contract(
    "online.update",
    expected_executables=1,
    donate_expected=(),
    collective_budget={},
    shape_buckets={0: (0, (_CONTRACT_ROWS,))},
)
def online_update_contract():
    import numpy as _np
    dim = 1 << _CONTRACT_BITS
    rng = _np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, dim, size=(_CONTRACT_ROWS,
                                                 _CONTRACT_K)), jnp.int32)
    val = jnp.asarray(rng.normal(size=(_CONTRACT_ROWS, _CONTRACT_K)),
                      jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=_CONTRACT_ROWS), jnp.float32)
    w = jnp.ones(_CONTRACT_ROWS, jnp.float32)
    fn = functools.partial(_online_update, loss_function="logistic")
    warm = jnp.asarray(rng.normal(size=dim) * 0.01, jnp.float32)
    zeros = jnp.zeros(dim, jnp.float32)
    lr, l2, bias = np.float32(0.5), np.float32(0.0), np.float32(0.0)
    cases = []
    for name, weights, acc in (("warm-start", warm, zeros),
                               ("steady", warm, jnp.abs(warm)),
                               ("post-refit", zeros, zeros)):
        cases.append(Case(name, fn,
                          (idx, val, y, w, weights, bias, acc, lr, l2),
                          group="online.update"))
    return cases
