"""Continuous learning on the serving stream (docs/online.md).

The missing middle of the closed loop: `StreamingEvaluator` joins
delayed labels to served predictions, `install_model` hot-swaps with
zero dropped requests, `RolloutDriver` canary-gates — this package
feeds the joined pairs back into training and ships the result.

- `learner`:  `OnlineLearner` — incremental VW updates on a fixed
  (rows, k) shape bucket, one compiled executable for life.
- `stream`:   `LabelFeed` — bounded minibatch buffer on evaluator joins.
- `loop`:     `ContinuousLearner` — drift-trip/floor-burn → refit →
  canary gate → promote or rollback, every transition journaled.
"""
from .learner import OnlineLearner
from .stream import LabelFeed
from .loop import (ContinuousLearner, ContinuousLearnerMachine,
                   OnlineAction, OnlineConfig, OnlineObservation)

__all__ = ["OnlineLearner", "LabelFeed", "ContinuousLearner",
           "ContinuousLearnerMachine", "OnlineAction", "OnlineConfig",
           "OnlineObservation"]
