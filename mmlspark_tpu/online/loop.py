"""ContinuousLearner: the loop that closes (docs/online.md).

```
            ┌────────────────────────────────────────────────┐
            v                                                │
  WATCHING ──trigger (drift trip | floor burn, pairs>=min)──┐│
            │                                               ││
            │                REFITTING                      ││
            │   snapshot -> drain feed -> partial_fit       ││
            │   -> [online.refit chaos site] -> candidate   ││
            │   (a raise rewinds the snapshot and retries)  ││
            │                                               v│
            │                CANARYING                       │
            │   deploy(candidate) -> rollout gate            │
            │     promoted  -> journal online.promote  ──────┘
            │     rejected  -> rewind snapshot,
            │                  journal online.rollback ──────┘
```

The policy is a pure state machine in the `RolloutStateMachine`
discipline: `ContinuousLearnerMachine` sees observations and returns
actions, does no I/O, holds no clock — exhaustively testable in
microseconds. `ContinuousLearner` wraps it with the impure halves
(feed drain, learner updates, ledger journaling, the deploy callable)
and pins the ledger event order every cycle journals:

    online.trip < online.refit < online.deploy <
        (online.promote | online.rollback)

Refits are retry-bounded (`online.refit_retries`) and every attempt
starts from the pre-refit snapshot, so a crashed attempt leaves no
partial update behind and a retry converges to the same weights — the
`online.refit` chaos site proves it. The incumbent keeps serving
through all of it: nothing installs until the candidate exists, and
the rollout gate owns install/promote/rollback from there.
"""
from __future__ import annotations

import time
from typing import Callable, NamedTuple, Optional

from ..reliability.metrics import reliability_metrics
from ..reliability.policy import RetryPolicy
from ..telemetry import names as tnames
from ..telemetry.spans import get_tracer

WATCHING = "watching"
REFITTING = "refitting"
CANARYING = "canarying"


class OnlineConfig(NamedTuple):
    """Loop knobs (docs/online.md#knobs)."""
    min_pairs: int = 64          # don't refit on a trickle
    max_refit_rows: int = 4096   # one refit's drain bound
    max_drift: float = 0.25      # PSI ceiling for the default observer
    poll_interval_s: float = 0.5
    cooldown_polls: int = 2      # quiet polls required after an outcome


class OnlineObservation(NamedTuple):
    """What the policy sees: trigger signals + buffered-pair depth."""
    drift_tripped: bool = False
    floor_burning: bool = False
    pairs: int = 0
    detail: Optional[dict] = None

    @property
    def triggered(self) -> bool:
        return self.drift_tripped or self.floor_burning


class OnlineAction(NamedTuple):
    kind: str                    # "refit" | "deploy"
    reason: Optional[str] = None


class ContinuousLearnerMachine:
    """Pure policy: observation in, action out, no I/O, no clock."""

    def __init__(self, config: Optional[OnlineConfig] = None):
        self.config = config or OnlineConfig()
        self.state = WATCHING
        self.last_outcome: Optional[str] = None
        self._cooldown = 0

    def on_observation(self, obs: OnlineObservation
                       ) -> Optional[OnlineAction]:
        if self.state != WATCHING:
            return None
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        if obs.triggered and obs.pairs >= self.config.min_pairs:
            self.state = REFITTING
            reason = "drift" if obs.drift_tripped else "floor-burn"
            return OnlineAction("refit", reason=reason)
        return None

    def on_refit_result(self, ok: bool) -> Optional[OnlineAction]:
        if self.state != REFITTING:
            return None
        if not ok:
            self.state = WATCHING
            self._cooldown = self.config.cooldown_polls
            self.last_outcome = "refit-failed"
            return None
        self.state = CANARYING
        return OnlineAction("deploy")

    def on_rollout_result(self, promoted: bool) -> None:
        if self.state != CANARYING:
            return
        self.state = WATCHING
        self._cooldown = self.config.cooldown_polls
        self.last_outcome = "promoted" if promoted else "rolled-back"


class ContinuousLearner:
    """The impure wrapper: drives the machine against real signals.

    Parameters
    ----------
    learner:  `OnlineLearner` holding the incremental training state.
    feed:     `LabelFeed` of joined (features, label, weight) pairs.
    deploy:   `fn(model) -> bool` — hand the candidate to the rollout
              gate (typically a `RolloutDriver` run; see
              `control.rollout`'s candidate-source hook) and report
              whether it promoted. A raise counts as a rejection.
    observe:  `fn() -> OnlineObservation`; defaults to reading the
              quality monitor's drift state + the feed depth.
    features_col / prediction_col: stamped onto produced candidates —
              must match the serving transform's columns.
    """

    def __init__(self, learner, feed,
                 deploy: Callable[[object], bool],
                 observe: Optional[Callable[[], OnlineObservation]] = None,
                 config: Optional[OnlineConfig] = None,
                 ledger=None, faults=None,
                 refit_policy: Optional[RetryPolicy] = None,
                 features_col: str = "features",
                 prediction_col: str = "prediction",
                 metrics=None, sleep=time.sleep):
        self.learner = learner
        self.feed = feed
        self.machine = ContinuousLearnerMachine(config)
        self.config = self.machine.config
        self._deploy = deploy
        self._observe = observe if observe is not None \
            else self._default_observe
        self._ledger = ledger
        self._faults = faults
        self._metrics = metrics if metrics is not None \
            else reliability_metrics
        self._sleep = sleep
        self.features_col = features_col
        self.prediction_col = prediction_col
        self._refit_policy = refit_policy if refit_policy is not None \
            else RetryPolicy(max_attempts=3, backoff=0.01,
                             backoff_factor=2.0, max_backoff=0.1,
                             jitter=0.0, sleep=sleep,
                             metric_name=tnames.ONLINE_REFIT_RETRIES,
                             metrics=self._metrics)
        self.cycles = 0

    # -- signals --------------------------------------------------------------
    def _default_observe(self) -> OnlineObservation:
        """Drift trip from the live quality monitor + floor burn from the
        process SLO engine's windowed verdict (telemetry/slo.py): an
        objective burning in BOTH its short and long windows — e.g. a
        quality-metric floor via `quality_objectives(metric_floor=...)` —
        flips `floor_burning`, so a model whose live metric sinks below
        the floor refits even when its feature distributions never
        drifted. The engine's no-data rule ("absence of evidence is not a
        burn") keeps an unconfigured or idle engine from false-tripping.
        An injected observer remains the test seam for both signals."""
        from ..telemetry import quality as tquality
        mon = tquality.get_monitor()
        worst, worst_col = 0.0, None
        if mon.active:
            for col, row in mon.drift().items():
                psi = row.get("psi")
                if (psi is not None
                        and row.get("live_count", 0) >= mon.min_live
                        and psi > worst):
                    worst, worst_col = float(psi), col
        tripped = worst > self.config.max_drift
        detail = ({"psi": round(worst, 4), "col": worst_col}
                  if tripped else None)
        burning = False
        try:
            from ..telemetry import slo as tslo
            verdict = tslo.get_engine().verdict(notify=False)
            hot = sorted(o["objective"]["name"]
                         for o in verdict.get("objectives", ())
                         if o.get("burning"))
            burning = bool(hot)
            if burning and detail is None:
                detail = {"slo": hot}
        except Exception:  # noqa: BLE001 - observation must not kill the loop
            burning = False
        return OnlineObservation(drift_tripped=tripped,
                                 floor_burning=burning,
                                 pairs=len(self.feed), detail=detail)

    def _journal(self, event: str, **attrs) -> None:
        get_tracer().event(event, **attrs)
        if self._ledger is not None:
            self._ledger.append_event(event, **attrs)

    # -- the refit ------------------------------------------------------------
    def _refit(self, snap: dict, reason: str):
        """Retry-bounded incremental refit. Every attempt rewinds to
        the pre-refit snapshot first, so the fault path leaves no
        partial update and retries converge to identical weights. The
        `online.refit` chaos site fires between the minibatch updates
        and candidate construction — mid-refit, state already dirty."""
        batch = self.feed.take(self.config.max_refit_rows)
        if batch is None:
            raise RuntimeError("label feed drained empty at refit time")
        idx, val, y, w = batch
        last_err: Optional[Exception] = None
        for att in self._refit_policy.attempts():
            self.learner.restore(snap)
            try:
                stats = self.learner.partial_fit(idx, val, y, w)
                if self._faults is not None:
                    self._faults.perturb("online.refit")
                reference = self._reference_profile(idx, val)
                model = self.learner.make_model(
                    features_col=self.features_col,
                    prediction_col=self.prediction_col,
                    reference_profile=reference, reason=reason)
                return model, stats
            except Exception as e:  # noqa: BLE001 - rewind, maybe retry
                last_err = e
                if att.is_last:
                    break
                att.retry()
        self.learner.restore(snap)
        raise last_err

    def _reference_profile(self, idx, val) -> Optional[dict]:
        """Fresh drift reference from the candidate's own scores on the
        refit sample — installing it re-baselines the drift gauges so a
        healed model doesn't keep tripping on the incumbent's frozen
        profile. Never fails the refit."""
        try:
            import numpy as np

            from ..telemetry.quality import DatasetProfile
            from .learner import _predict_sparse
            link = ("logistic"
                    if self.learner.params.loss_function == "logistic"
                    else None)
            score = np.asarray(_predict_sparse(
                self.learner._weights, self.learner._bias,
                idx, val, link=link))
            pred = ((score > 0.5).astype(np.float64)
                    if link == "logistic" else score.astype(np.float64))
            prof = DatasetProfile.fit({"prediction": pred})
            return prof.state()
        except Exception:  # noqa: BLE001 - reference is best-effort
            return None

    # -- one cycle ------------------------------------------------------------
    def run_once(self) -> dict:
        """One observation -> (maybe) one full trip/refit/deploy cycle.
        Returns a status dict; never raises on refit or deploy failure
        (those are outcomes, counted and journaled)."""
        obs = self._observe()
        action = self.machine.on_observation(obs)
        if action is None:
            return {"state": self.machine.state, "action": None,
                    "pairs": obs.pairs}
        self.cycles += 1
        self._metrics.inc(tnames.ONLINE_TRIPS)
        self._journal(tnames.ONLINE_TRIP_EVENT, reason=action.reason,
                      pairs=obs.pairs, **(obs.detail or {}))
        snap = self.learner.snapshot()
        try:
            model, stats = self._refit(snap, action.reason)
        except Exception as e:  # noqa: BLE001 - refit failed: stay put
            self.machine.on_refit_result(False)
            return {"state": self.machine.state, "action": "refit",
                    "outcome": "refit-failed", "error": str(e)}
        from ..telemetry.lineage import model_version
        version = model_version(model, content=True).version
        self._metrics.inc(tnames.ONLINE_REFITS)
        self._journal(tnames.ONLINE_REFIT_EVENT, version=version,
                      updates=stats["updates"],
                      examples=stats["examples"],
                      loss=round(stats["loss"], 6))
        self.machine.on_refit_result(True)
        self._journal(tnames.ONLINE_DEPLOY_EVENT, version=version)
        try:
            promoted = bool(self._deploy(model))
        except Exception:  # noqa: BLE001 - a raising gate is a rejection
            promoted = False
        if promoted:
            self._metrics.inc(tnames.ONLINE_PROMOTIONS)
            self._journal(tnames.ONLINE_PROMOTE_EVENT, version=version)
        else:
            # rejected candidate: the gate already restored the
            # incumbent fleet-side; rewind the learner to match
            self.learner.restore(snap)
            self._metrics.inc(tnames.ONLINE_ROLLBACKS)
            self._journal(tnames.ONLINE_ROLLBACK_EVENT, version=version)
        self.machine.on_rollout_result(promoted)
        return {"state": self.machine.state, "action": "refit",
                "outcome": "promoted" if promoted else "rolled-back",
                "version": version}

    def run(self, max_cycles: int = 1,
            stop: Optional[Callable[[], bool]] = None) -> dict:
        """Poll until `max_cycles` full cycles completed (or `stop()`).
        Returns the last `run_once` status."""
        status = {"state": self.machine.state, "action": None}
        done = 0
        while done < max_cycles and (stop is None or not stop()):
            status = self.run_once()
            if status.get("outcome") is not None:
                done += 1
            else:
                self._sleep(self.config.poll_interval_s)
        return status

    def status(self) -> dict:
        return {"state": self.machine.state,
                "last_outcome": self.machine.last_outcome,
                "cycles": self.cycles,
                "feed": self.feed.stats(),
                "learner": {"updates": self.learner.updates,
                            "examples": self.learner.examples,
                            "refits": self.learner.refits}}
