"""LabelFeed: the bounded bridge from label joins to minibatches.

The serving path knows features by request id (the client reads the id
back from the `X-Request-Id` header); the `StreamingEvaluator` knows
when a delayed label joins its prediction. `LabelFeed` subscribes to
those joins (`on_join` hook, PR 17) and assembles the third thing the
learner needs: (features, label, weight) triples, buffered as
minibatch-ready arrays.

Both buffers are bounded and every loss is COUNTED, never raised —
the feed lives on the serving path's side of the house and inherits
its hostility assumptions:

- features whose label never arrives age out of the bounded feature
  window silently (they were never a pair);
- a join whose features already aged out counts `online.feed.dropped`;
- pair-buffer overflow evicts oldest-first, counted the same.

Determinism: the feed does no I/O and holds no clock — replaying the
same (record_features, on_join) sequence yields byte-identical
minibatches, which is what the chaos tests lean on.
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Optional, Tuple

import numpy as np

from ..reliability.metrics import reliability_metrics
from ..telemetry import names as tnames


class LabelFeed:
    """Bounded (features, label, weight) minibatch buffer.

    Parameters
    ----------
    evaluator:     optional `StreamingEvaluator` to subscribe to; when
                   None, call `on_join(rid, pred, label)` directly (the
                   deterministic-replay path tests use).
    max_pairs:     joined-pair buffer bound; overflow evicts oldest.
    max_features:  pending-features window bound (predictions whose
                   label hasn't arrived yet).
    """

    def __init__(self, evaluator=None, max_pairs: int = 4096,
                 max_features: int = 8192, default_weight: float = 1.0,
                 metrics=None):
        self.max_pairs = max(int(max_pairs), 1)
        self.max_features = max(int(max_features), 1)
        self.default_weight = float(default_weight)
        self._metrics = metrics if metrics is not None \
            else reliability_metrics
        self._lock = threading.Lock()
        self._features: OrderedDict = OrderedDict()  # rid -> (idx, val, w)
        self._pairs: deque = deque()                 # (idx, val, y, w)
        self.joined_total = 0
        self.dropped_total = 0
        if evaluator is not None:
            evaluator.subscribe(self.on_join)

    # -- feature side ---------------------------------------------------------
    def record_features(self, request_ids, idx, val, weights=None) -> None:
        """Stage a served batch's features under their request ids.
        idx/val are the (n, k) hashed-pair arrays the row was scored
        with; per-row weight defaults to `default_weight`."""
        idx = np.asarray(idx, np.int32)
        val = np.asarray(val, np.float32)
        if idx.ndim != 2 or idx.shape != val.shape:
            raise ValueError("idx/val must be matching (n, k) arrays")
        if len(request_ids) != idx.shape[0]:
            raise ValueError("one request id per row required")
        if weights is None:
            weights = [self.default_weight] * idx.shape[0]
        with self._lock:
            for i, rid in enumerate(request_ids):
                self._features[str(rid)] = (idx[i].copy(), val[i].copy(),
                                            float(weights[i]))
                while len(self._features) > self.max_features:
                    # silent age-out: not yet a pair, nothing was lost
                    self._features.popitem(last=False)

    # -- join side (the evaluator calls this) ---------------------------------
    def on_join(self, request_id: str, prediction, label) -> None:
        """One joined (prediction, label) pair from the evaluator. The
        prediction itself is not buffered — training consumes the
        features that PRODUCED it, plus the label."""
        del prediction
        with self._lock:
            feats = self._features.pop(str(request_id), None)
            if feats is None:
                self.dropped_total += 1
                self._metrics.inc(tnames.ONLINE_FEED_DROPPED)
                return
            idx_row, val_row, weight = feats
            self._pairs.append((idx_row, val_row, float(label), weight))
            while len(self._pairs) > self.max_pairs:
                self._pairs.popleft()
                self.dropped_total += 1
                self._metrics.inc(tnames.ONLINE_FEED_DROPPED)
            self.joined_total += 1
            depth = len(self._pairs)
        self._metrics.inc(tnames.ONLINE_FEED_PAIRS)
        self._metrics.set_gauge(tnames.ONLINE_BUFFER_PAIRS, depth)

    # -- learner side ---------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._pairs)

    def take(self, max_rows: Optional[int] = None
             ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                 np.ndarray, np.ndarray]]:
        """Drain up to max_rows buffered pairs, FIFO, as (idx, val, y,
        w) arrays. Rows of differing pair width are right-padded with
        idx 0 / val 0 (the zero-contribution convention). Returns None
        when empty."""
        with self._lock:
            n = len(self._pairs)
            if max_rows is not None:
                n = min(n, int(max_rows))
            if n == 0:
                return None
            rows = [self._pairs.popleft() for _ in range(n)]
            depth = len(self._pairs)
        self._metrics.set_gauge(tnames.ONLINE_BUFFER_PAIRS, depth)
        k = max(r[0].shape[0] for r in rows)
        idx = np.zeros((n, k), np.int32)
        val = np.zeros((n, k), np.float32)
        y = np.empty(n, np.float32)
        w = np.empty(n, np.float32)
        for i, (ri, rv, ry, rw) in enumerate(rows):
            idx[i, :ri.shape[0]] = ri
            val[i, :rv.shape[0]] = rv
            y[i], w[i] = ry, rw
        return idx, val, y, w

    def stats(self) -> dict:
        with self._lock:
            return {"pairs": len(self._pairs),
                    "pending_features": len(self._features),
                    "joined_total": self.joined_total,
                    "dropped_total": self.dropped_total}
