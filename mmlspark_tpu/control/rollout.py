"""Progressive delivery: staged candidate rollout with chaos-proven
auto-rollback.

PR 14 shipped the rollback *signal* (canary gauges, `canary_objectives`
burn verdicts, `canary_watch_rules` trips) and explicitly deferred
actuation. `RolloutDriver` is that actuation: it `install_model`s a
candidate on a configurable traffic fraction of workers, polls the fleet
(`scrape_cluster(versions=True, slo=True)`), and drives a DETERMINISTIC
state machine —

    pending --start()--> canary[step 0] --healthy xN--> canary[step 1]
        ... --healthy xN--> soak --healthy xM--> promoted
    canary/soak --burn or watch trip--> rolling_back --> rolled_back
                                 (rollback exhausted) --> failed

— auto-promoting through the staged path or auto-rolling-back via
re-`install_model` of the incumbent. Every transition is journaled to
the RunLedger (file order pins `deploy < burn < rollback < recovered`)
and emitted as a `control.rollout.*` event.

The state machine (`RolloutStateMachine`) is a PURE function of its
observations: no sockets, no clocks — seeded observation schedules drive
every transition in tests. The driver wraps it with the fleet I/O:
scraping (chaos site `control.rollout.poll`), installs, and the
retry-bounded (`reliability.RetryPolicy`), IDEMPOTENT rollback — a
double rollback is a no-op, and a rollback racing the seeded
`serving.swap` fault retries until the incumbent serves again.
"""
from __future__ import annotations

import math
import time
from collections import deque
from typing import NamedTuple, Optional

from ..reliability.metrics import reliability_metrics
from ..reliability.policy import RetryPolicy
from ..telemetry import names as tnames
from ..telemetry.slo import verdict_burning
from ..telemetry.spans import get_tracer
from ..telemetry.watch import evaluate_rule

# -- states (module constants so tests read like the diagram) -------------
PENDING = "pending"
CANARY = "canary"
SOAK = "soak"
PROMOTED = "promoted"
ROLLING_BACK = "rolling_back"
ROLLED_BACK = "rolled_back"
FAILED = "failed"


class RolloutConfig(NamedTuple):
    """Rollout knobs (docs/control.md "Rollout state machine").

    `traffic_steps` are ascending worker-fraction stages ending at 1.0;
    `step_polls` healthy observations clear one stage, `soak_polls` more
    at full traffic auto-promote. `recover_polls` bounds the
    post-rollback wait for the fleet verdict to return to ok."""
    traffic_steps: tuple = (0.25, 0.5, 1.0)
    step_polls: int = 2
    soak_polls: int = 3
    poll_interval_s: float = 1.0
    scrape_window_s: Optional[float] = 60.0
    recover_polls: int = 60
    history: int = 64   # retained merged-metric samples for watch rules


class Observation(NamedTuple):
    """One poll round's verdict, reduced to what the machine keys on."""
    burning: bool = False     # fleet or candidate SLO error budget burning
    tripped: bool = False     # a canary watch rule breached
    detail: Optional[dict] = None

    @property
    def healthy(self) -> bool:
        return not (self.burning or self.tripped)


class Action(NamedTuple):
    """What the machine asks the driver to do next."""
    kind: str                          # install | promote | rollback
    fraction: Optional[float] = None   # install: target worker fraction
    reason: Optional[str] = None       # rollback: burn | watch-trip


class RolloutStateMachine:
    """The pure transition core: feed observations, get actions.

    Deterministic and I/O-free — the same observation sequence always
    produces the same action sequence, so seeded schedules pin every
    transition without sockets (tests/test_control.py)."""

    def __init__(self, config: Optional[RolloutConfig] = None):
        config = config if config is not None else RolloutConfig()
        steps = tuple(float(f) for f in config.traffic_steps)
        if not steps or steps[-1] != 1.0:
            raise ValueError("traffic_steps must end at 1.0 (full traffic)")
        if any(b <= a for a, b in zip(steps, steps[1:])) \
                or steps[0] <= 0.0:
            raise ValueError("traffic_steps must be ascending in (0, 1]")
        if config.step_polls < 1 or config.soak_polls < 0:
            raise ValueError("step_polls >= 1 and soak_polls >= 0 required")
        self.config = config._replace(traffic_steps=steps)
        self.state = PENDING
        self.step = -1            # index into traffic_steps
        self._healthy = 0         # consecutive healthy polls this stage

    @property
    def fraction(self) -> float:
        """The traffic fraction currently targeted for the candidate."""
        if self.state in (PENDING, ROLLING_BACK, ROLLED_BACK, FAILED):
            return 0.0
        if self.state in (SOAK, PROMOTED):
            return 1.0
        return self.config.traffic_steps[self.step]

    def start(self) -> Action:
        if self.state != PENDING:
            raise RuntimeError(f"rollout already started (state={self.state})")
        self.state = CANARY
        self.step = 0
        self._healthy = 0
        return Action("install", fraction=self.config.traffic_steps[0])

    def on_observation(self, obs: Observation) -> Optional[Action]:
        """One poll round. Returns the action to take, or None (keep
        watching). Observations landing in a terminal state — or during
        a rollback already in flight — are inert, which is half of the
        double-rollback idempotency (the driver's installed-set is the
        other half)."""
        if self.state not in (CANARY, SOAK):
            return None
        if not obs.healthy:
            self.state = ROLLING_BACK
            self._healthy = 0
            return Action("rollback",
                          reason="burn" if obs.burning else "watch-trip")
        self._healthy += 1
        if self.state == CANARY:
            if self._healthy >= self.config.step_polls:
                self._healthy = 0
                if self.step + 1 < len(self.config.traffic_steps):
                    self.step += 1
                    return Action(
                        "install",
                        fraction=self.config.traffic_steps[self.step])
                self.state = SOAK
            return None
        if self._healthy >= self.config.soak_polls:
            self.state = PROMOTED
            return Action("promote")
        return None

    def on_rollback_result(self, ok: bool) -> None:
        """Commit the rollback outcome. Idempotent: only a rollback in
        flight transitions; a second call (double rollback) is a no-op."""
        if self.state == ROLLING_BACK:
            self.state = ROLLED_BACK if ok else FAILED


class RolloutDriver:
    """The I/O wrapper: installs, fleet scrapes, journals, retries.

    `workers` maps a stable worker name to its serving transform (the
    object `serve_pipeline` mounts — anything with `install_model(model,
    if_changed=...)` and a `version`). Order is the install order: the
    first `ceil(fraction * N)` workers carry the candidate at each step,
    so a given fraction always names the same workers.

    `observe` (tests) replaces the fleet scrape with any callable
    returning an `Observation` (or None for "scrape failed, skip the
    round"); `registry_address` arms the real scrape path. `ledger`
    defaults to the configured run ledger (may be None: events still
    emit, journaling is skipped). `faults` arms the `control.rollout.poll`
    chaos site; the `serving.swap` site fires inside each transform's own
    injector during (re-)installs.

    `candidate` may be a model or a zero-arg callable producing one
    (candidate-source hook): the callable is resolved at construction so
    the driver's content-addressed `candidate_version` names the exact
    artifact the rollout ships."""

    def __init__(self, workers, incumbent, candidate,
                 registry_address: Optional[str] = None,
                 config: Optional[RolloutConfig] = None,
                 observe=None, ledger=None, faults=None,
                 rollback_policy: Optional[RetryPolicy] = None,
                 scrape_timeout: float = 5.0,
                 clock=time.monotonic, sleep=time.sleep, metrics=None):
        self._workers = list(workers.items()) if isinstance(workers, dict) \
            else [(name, t) for name, t in workers]
        if not self._workers:
            raise ValueError("need at least one worker")
        if registry_address is None and observe is None:
            raise ValueError("need registry_address (fleet scrape) or "
                             "observe (injected observations)")
        self.machine = RolloutStateMachine(config)
        self.config = self.machine.config
        self.registry_address = registry_address
        self.incumbent = incumbent
        # candidate-source hook: a zero-arg callable is resolved here,
        # once — so continuous-learning producers (online.loop) can hand
        # the driver a "build my freshest candidate" thunk and the
        # content-addressed version below names what actually ships
        if callable(candidate) and not hasattr(candidate, "transform"):
            candidate = candidate()
        self.candidate = candidate
        self._observe_fn = observe
        self.scrape_timeout = scrape_timeout
        self._clock = clock
        self._sleep = sleep
        self._faults = faults
        self._metrics = metrics if metrics is not None \
            else reliability_metrics
        if ledger is None:
            from ..telemetry.lineage import get_run_ledger
            ledger = get_run_ledger()
        self._ledger = ledger
        self._rollback_policy = rollback_policy if rollback_policy \
            is not None else RetryPolicy(
                max_attempts=4, backoff=0.05, backoff_factor=2.0,
                max_backoff=0.5, jitter=0.0, sleep=sleep,
                metric_name=tnames.CONTROL_ROLLOUT_ROLLBACK_RETRIES)
        self._candidate_on: set = set()   # worker names serving candidate
        self._rolled_back = False
        from ..telemetry.lineage import canary_watch_rules, model_version
        self._watch_rules = canary_watch_rules()
        self._history: deque = deque(maxlen=max(int(self.config.history), 8))
        self.candidate_version = model_version(candidate).version
        self.incumbent_version = model_version(incumbent).version
        if self.candidate_version == self.incumbent_version:
            raise ValueError("candidate and incumbent are the same version")

    # -- journaling -----------------------------------------------------------
    def _journal(self, event: str, **attrs) -> None:
        get_tracer().event(event, **attrs)
        if self._ledger is not None:
            self._ledger.append_event(
                event, candidate=self.candidate_version,
                incumbent=self.incumbent_version, **attrs)

    # -- observation ----------------------------------------------------------
    def _observe(self) -> Optional[Observation]:
        if self._observe_fn is not None:
            return self._observe_fn()
        try:
            if self._faults is not None:
                self._faults.perturb("control.rollout.poll")
            from ..telemetry.exposition import scrape_cluster
            snap = scrape_cluster(self.registry_address, slo=True,
                                  versions=True,
                                  timeout=self.scrape_timeout,
                                  window=self.config.scrape_window_s)
        except Exception:  # noqa: BLE001 - a failed scrape skips the round
            self._metrics.inc(tnames.CONTROL_ROLLOUT_POLL_ERRORS)
            return None
        burning = verdict_burning(snap.slo)
        by_version = (snap.versions or {}).get("slo_by_version") or {}
        burning = burning or verdict_burning(
            by_version.get(self.candidate_version))
        self._history.append((self._clock(), snap.merged))
        tripped, trip = False, None
        for rule in self._watch_rules:
            series = [(t, m[rule.key]) for t, m in self._history
                      if rule.key in m]
            trip = evaluate_rule(rule, series)
            if trip is not None:
                tripped = True
                break
        return Observation(burning=burning, tripped=tripped,
                           detail={"trip": trip} if trip else None)

    # -- actuation ------------------------------------------------------------
    def _install_fraction(self, fraction: float) -> list:
        """Install the candidate on the first ceil(fraction*N) workers
        not already carrying it. A failed candidate install triggers an
        immediate rollback (the candidate could not even deploy)."""
        n = len(self._workers)
        # ceil with a float-slop guard: 0.5 * 4 must be 2 workers, not 3
        want = min(n, max(1, math.ceil(fraction * n - 1e-9)))
        fresh = []
        for name, transform in self._workers[:want]:
            if name in self._candidate_on:
                continue
            transform.install_model(self.candidate)
            self._candidate_on.add(name)
            fresh.append(name)
        self._metrics.inc(tnames.CONTROL_ROLLOUT_STEPS)
        self._metrics.set_gauge(tnames.CONTROL_ROLLOUT_FRACTION, fraction)
        return fresh

    def rollback(self, reason: str = "manual") -> bool:
        """Re-install the incumbent on every worker carrying the
        candidate. IDEMPOTENT: a second call returns immediately (the
        installed-set is empty and the journal/counters are untouched);
        per-worker installs use `if_changed=True`, so even a re-driven
        rollback cannot double-swap a worker. Retry-bounded: each
        worker's re-install runs under the driver's RetryPolicy — a
        `serving.swap` fault mid-rollback retries until the incumbent
        serves (True) or the policy exhausts (False, state `failed`)."""
        if self._rolled_back:
            return True
        self._rolled_back = True
        if self.machine.state != ROLLING_BACK:
            # direct/manual rollback: take the machine there first so the
            # outcome transition below lands (inert if already terminal)
            self.machine.state = ROLLING_BACK
        targets = sorted(self._candidate_on)
        ok = True
        for name, transform in self._workers:
            if name not in self._candidate_on:
                continue
            if self._rollback_worker(transform):
                self._candidate_on.discard(name)
            else:
                ok = False
        self._metrics.inc(tnames.CONTROL_ROLLOUT_ROLLBACKS)
        self._metrics.set_gauge(tnames.CONTROL_ROLLOUT_FRACTION, 0.0)
        self.machine.on_rollback_result(ok)
        self._journal(tnames.CONTROL_ROLLOUT_ROLLBACK_EVENT, reason=reason,
                      ok=ok, workers=targets)
        return ok

    def _rollback_worker(self, transform) -> bool:
        last: Optional[Exception] = None
        for att in self._rollback_policy.attempts():
            try:
                transform.install_model(self.incumbent, if_changed=True)
                return True
            except Exception as e:  # noqa: BLE001 - retried under policy
                last = e
                att.retry()
        del last
        return False

    # -- the loop -------------------------------------------------------------
    def run(self) -> dict:
        """Drive the rollout to a terminal state; returns `status()`.
        Synchronous — run it on its own thread next to live load (the
        fleet bench does) or inline in tests with injected observe/sleep."""
        action = self.machine.start()
        # deploy is journaled FIRST — even a candidate that cannot
        # install keeps the pinned ledger order deploy < burn < rollback
        self._journal(tnames.CONTROL_ROLLOUT_DEPLOY_EVENT,
                      fraction=action.fraction)
        self._install_or_rollback(action)
        while self.machine.state in (CANARY, SOAK):
            self._sleep(self.config.poll_interval_s)
            obs = self._observe()
            if obs is None:
                continue
            action = self.machine.on_observation(obs)
            if action is None:
                continue
            if action.kind == "install":
                self._install_or_rollback(action)
            elif action.kind == "promote":
                self._metrics.inc(tnames.CONTROL_ROLLOUT_PROMOTIONS)
                self._journal(tnames.CONTROL_ROLLOUT_PROMOTE_EVENT)
            elif action.kind == "rollback":
                detail = (obs.detail or {}) if obs is not None else {}
                self._journal(tnames.CONTROL_ROLLOUT_BURN_EVENT,
                              reason=action.reason, **detail)
                self.rollback(reason=action.reason)
                self._await_recovery()
        return self.status()

    def _install_or_rollback(self, action: Action):
        """Run one install step; a deploy failure (the candidate can't
        even install — e.g. its `serving.swap` chaos fired) rolls back
        whatever fraction already carries it."""
        try:
            fresh = self._install_fraction(action.fraction)
            self._journal(tnames.CONTROL_ROLLOUT_STEP_EVENT,
                          fraction=action.fraction, workers=fresh)
            return fresh
        except Exception as e:  # noqa: BLE001 - deploy failure => rollback
            self.machine.state = ROLLING_BACK
            self._journal(tnames.CONTROL_ROLLOUT_BURN_EVENT,
                          reason="deploy-failure", error=str(e))
            self.rollback(reason="deploy-failure")
            self._await_recovery()
            return None

    def _await_recovery(self) -> None:
        """Post-rollback: poll until the fleet verdict reads healthy
        again (bounded by recover_polls), then journal `recovered`."""
        ok = False
        for _ in range(max(int(self.config.recover_polls), 0)):
            obs = self._observe()
            if obs is not None and obs.healthy:
                ok = True
                break
            self._sleep(self.config.poll_interval_s)
        self._journal(tnames.CONTROL_ROLLOUT_RECOVERED_EVENT, ok=ok)

    def status(self) -> dict:
        return {"state": self.machine.state,
                "step": self.machine.step,
                "fraction": self.machine.fraction,
                "candidate": self.candidate_version,
                "incumbent": self.incumbent_version,
                "candidate_on": sorted(self._candidate_on)}
