"""Serving control plane: the ACTUATION tier over serving, registry, and
telemetry.

The observability arc (canary verdicts, SLO burn rates, per-worker
queue-depth/p99 gauges, fleet scrape/merge) built the sensors; nothing
acted on them. This package closes the loop:

- `rollout` — progressive delivery: `RolloutDriver` installs a candidate
  model on staged traffic fractions, watches the fleet's canary/SLO
  verdicts through a deterministic state machine, and auto-promotes or
  auto-rolls-back (idempotent, retry-bounded) with every transition
  journaled to the RunLedger and emitted as `control.rollout.*` events.
- `actuators` — fleet actuators: `WeightedRouter` (target selection
  weighted by scraped queue depth and windowed p99), `BurnAwareAdmission`
  (shed-before-queue with Retry-After while the error budget burns), and
  `FleetScaler` (occupancy-driven drain/spawn hooks over the existing
  per-worker graceful drain).

Everything here is host-side control logic — pure Python over the
telemetry/serving substrates, no compiled hot path (pinned by
tests/test_control.py: importing this package must not import jax).
See docs/control.md.
"""
from .actuators import BurnAwareAdmission, FleetScaler, WeightedRouter
from .rollout import (Action, Observation, RolloutConfig, RolloutDriver,
                      RolloutStateMachine)

__all__ = [
    "Action",
    "BurnAwareAdmission",
    "FleetScaler",
    "Observation",
    "RolloutConfig",
    "RolloutDriver",
    "RolloutStateMachine",
    "WeightedRouter",
]
