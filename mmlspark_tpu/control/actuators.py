"""SLO-burn-aware fleet actuators: the knobs the control loop turns.

Three actuators, one per layer of the serving stack:

- `WeightedRouter` — the ROUTING tier. A `RegistryClient` whose target
  selection is smooth-weighted-round-robin over per-worker weights
  derived from the fleet scrape (queue depth x windowed p99): a worker
  whose queue grows or whose tail stretches sees its share of new
  requests drop, instead of the blind rotation feeding it at full rate
  until it trips the SLO.
- `BurnAwareAdmission` — the ADMISSION tier. `ServingServer` consults it
  at enqueue: while the error budget burns, excess load is shed with
  503 + Retry-After BEFORE it queues (shed-before-queue), so a burning
  worker's queue depth stays bounded instead of absorbing the backlog
  that keeps its p99 pinned past the objective. The verdict is cached
  (`refresh_s`) so the hot path never pays an SLO evaluation per request.
- `FleetScaler` — the FLEET tier. Pure occupancy-driven spawn/drain
  decisions (`decide`) plus a cooldown-debounced stateful wrapper
  (`observe`) that fires caller-provided hooks; the hooks are the
  existing per-worker lifecycle (`serve_pipeline` up, graceful drain
  down), so the scaler stays policy, not mechanism.

All three are deterministic given their inputs (the SWRR rotation is a
pure function of the weight table; `decide` is a pure function of the
occupancy window) — seeded tests pin their behavior without load.
See docs/control.md "Actuators".
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..reliability.metrics import reliability_metrics
from ..telemetry import names as tnames
from ..io.registry import RegistryClient

_DEFAULT_WEIGHT = 100   # weight of a worker the scrape hasn't costed yet


class WeightedRouter(RegistryClient):
    """RegistryClient with smooth-weighted-round-robin target selection.

    Weights are integers (share of new requests, relative); unknown
    targets default to 100, so an unweighted router IS the plain
    round-robin client. `update_from_scrape` turns a fleet
    `ClusterSnapshot` into weights with cost = (1 + queue_depth) x
    max(p99_ms, 1): the cheapest worker keeps weight 100 and a worker
    N times costlier gets ~100/N — a delay-faulted worker's share drops
    while the fleet keeps answering (the actuator acceptance).

    SWRR (nginx's algorithm): each pick adds every target's weight to
    its current credit, routes to the max, then subtracts the total —
    deterministic, starvation-free (any positive weight gets a turn),
    and maximally spread (no bursts of the heavy target back-to-back).
    """

    def __init__(self, registry_address: str, name: str,
                 refresh_every: int = 64, timeout: float = 30.0):
        # set before super().__init__: it calls refresh() -> _next_target
        # state must exist
        self._weights: dict = {}   # address -> int weight
        self._current: dict = {}   # address -> SWRR credit
        super().__init__(registry_address, name,
                         refresh_every=refresh_every, timeout=timeout)

    @property
    def weights(self) -> dict:
        with self._lock:
            return dict(self._weights)

    def set_weights(self, weights: dict) -> None:
        """Replace the weight table ({address: int}); floors at 1 (a
        zero/negative weight would starve the SWRR rotation — drain a
        worker by unregistering it, not by zeroing it)."""
        cleaned = {addr: max(1, int(w)) for addr, w in weights.items()}
        with self._lock:
            self._weights = cleaned
            # drop credit for departed targets; keep credit for survivors
            # so a weight refresh doesn't reset the rotation's spread
            self._current = {a: self._current.get(a, 0) for a in cleaned}
        reliability_metrics.inc(tnames.CONTROL_ROUTER_UPDATES)
        for addr, w in cleaned.items():
            reliability_metrics.set_gauge(
                tnames.control_router_weight(addr), float(w))

    def update_from_scrape(self, snapshot) -> dict:
        """Derive weights from a `scrape_cluster` ClusterSnapshot and
        install them. Returns the weight table (for tests/logging)."""
        from ..telemetry.exposition import state_snapshot
        costs = {}
        for info, state in snapshot.workers:
            flat = state_snapshot(state)
            depth = float(flat.get(tnames.SERVING_QUEUE_DEPTH, 0.0) or 0.0)
            p99 = float(
                flat.get(tnames.SERVING_REQUEST_E2E + ".p99", 0.0) or 0.0)
            costs[f"{info.host}:{info.port}"] = \
                (1.0 + max(depth, 0.0)) * max(p99, 1.0)
        if not costs:
            return {}
        floor = min(costs.values())
        weights = {addr: max(1, round(_DEFAULT_WEIGHT * floor / cost))
                   for addr, cost in costs.items()}
        self.set_weights(weights)
        return weights

    def _next_target(self):
        """SWRR pick over live targets; falls back to the base rotation
        when no weight table is installed."""
        with self._lock:
            live = [t for t in self._targets if t.address not in self._dead]
            if not live:
                return None
            if not self._weights:
                t = live[self._count % len(live)]
                self._count += 1
                return t
            total = 0
            best, best_credit = None, None
            for t in live:
                addr = f"{t.host}:{t.port}"
                w = self._weights.get(addr, _DEFAULT_WEIGHT)
                total += w
                credit = self._current.get(addr, 0) + w
                self._current[addr] = credit
                if best_credit is None or credit > best_credit:
                    best, best_credit = t, credit
            self._current[f"{best.host}:{best.port}"] -= total
            self._count += 1
            return best


class BurnAwareAdmission:
    """Shed-before-queue admission control for `ServingServer`.

    `should_shed(queue_depth)` is consulted at enqueue, BEFORE the
    max_queue check: it returns True when the SLO error budget is
    burning AND the partition queue already holds more than
    `queue_allowance` requests — the request is answered 503 with
    `Retry-After: retry_after_s` instead of queueing behind a backlog
    the worker demonstrably can't drain inside its objective. In-flight
    and under-allowance requests still queue, so a short burn sheds the
    excess, not the service.

    The burn verdict is CACHED: `verdict_fn` (default: this process's
    SLO engine, `get_engine().verdict(notify=False)`) runs at most once
    per `refresh_s` — the serving hot path pays a monotonic-clock read
    and a bool, never an SLO evaluation. A verdict_fn that raises reads
    as not-burning (fail open: admission must never take down a healthy
    worker)."""

    def __init__(self, verdict_fn: Optional[Callable] = None,
                 refresh_s: float = 0.25, retry_after_s: float = 1.0,
                 queue_allowance: int = 0, clock=time.monotonic):
        if verdict_fn is None:
            def verdict_fn():
                from ..telemetry.slo import get_engine
                return get_engine().verdict(notify=False)
        self._verdict_fn = verdict_fn
        self.refresh_s = float(refresh_s)
        self.retry_after_s = float(retry_after_s)
        self.queue_allowance = int(queue_allowance)
        self._clock = clock
        self._lock = threading.Lock()
        self._burning = False
        self._stamp: Optional[float] = None

    def burning(self) -> bool:
        """The cached burn verdict, refreshed at most every refresh_s."""
        now = self._clock()
        with self._lock:
            if self._stamp is not None \
                    and now - self._stamp < self.refresh_s:
                return self._burning
            self._stamp = now
        try:
            verdict = self._verdict_fn()
        except Exception:  # noqa: BLE001 - fail open
            verdict = None
        from ..telemetry.slo import verdict_burning
        burning = verdict_burning(verdict)
        with self._lock:
            self._burning = burning
        return burning

    def should_shed(self, queue_depth: int) -> bool:
        return queue_depth > self.queue_allowance and self.burning()


class FleetScaler:
    """Occupancy-driven worker count policy: spawn when the fleet runs
    hot for a full window, drain when it runs cold — mechanism stays
    with the caller (`spawn`/`drain` hooks, e.g. `serve_pipeline` /
    graceful drain).

    `decide` is PURE: given the last-`window` occupancy samples (0..1,
    e.g. fleet batch occupancy or queue_depth/max_queue) and the worker
    count, it returns "spawn", "drain", or None. `observe` wraps it with
    the stateful parts — sample accumulation and a `cooldown`-round
    debounce so one scale action settles before the next fires."""

    def __init__(self, spawn: Optional[Callable] = None,
                 drain: Optional[Callable] = None,
                 high: float = 0.75, low: float = 0.15,
                 window: int = 3, cooldown: int = 2,
                 min_workers: int = 1,
                 max_workers: Optional[int] = None):
        if not 0.0 <= low < high <= 1.0:
            raise ValueError("need 0 <= low < high <= 1")
        self.spawn_hook = spawn
        self.drain_hook = drain
        self.high = float(high)
        self.low = float(low)
        self.window = max(1, int(window))
        self.cooldown = max(0, int(cooldown))
        self.min_workers = max(1, int(min_workers))
        self.max_workers = max_workers
        self._samples: list = []
        self._cooldown_left = 0

    def decide(self, occupancy_series, n_workers: int) -> Optional[str]:
        """Pure policy: a full window above `high` (and room to grow)
        says spawn; a full window at/below `low` (and room to shrink)
        says drain; anything else holds."""
        series = list(occupancy_series)[-self.window:]
        if len(series) < self.window:
            return None
        if all(s >= self.high for s in series) \
                and (self.max_workers is None
                     or n_workers < self.max_workers):
            return "spawn"
        if all(s <= self.low for s in series) \
                and n_workers > self.min_workers:
            return "drain"
        return None

    def observe(self, occupancy: float, n_workers: int) -> Optional[str]:
        """Feed one fleet occupancy sample; fires the matching hook when
        the windowed policy says so (debounced by `cooldown` rounds).
        Returns the action taken, or None."""
        self._samples.append(float(occupancy))
        del self._samples[:-self.window]
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            return None
        action = self.decide(self._samples, n_workers)
        if action is None:
            return None
        self._samples.clear()     # a scale action invalidates the window
        self._cooldown_left = self.cooldown
        if action == "spawn":
            reliability_metrics.inc(tnames.CONTROL_SCALER_SPAWNS)
            if self.spawn_hook is not None:
                self.spawn_hook()
        else:
            reliability_metrics.inc(tnames.CONTROL_SCALER_DRAINS)
            if self.drain_hook is not None:
                self.drain_hook()
        return action
