"""Quantile feature binning: float features -> uint8 bin ids + bin upper bounds.

Role-equivalent to LightGBM's native BinMapper/Dataset construction, which the
reference reaches through per-value JNI streaming (lightgbm/TrainUtils.scala:33-186,
LightGBMUtils.scala:204-286 — `LGBM_DatasetCreateFromMats`). TPU-first design:
binning happens once on host over whole columns (vectorized numpy, no row loop),
producing a dense (n_rows, n_features) uint8 matrix that lives in HBM — 4-8x
smaller than f32 features, which is what makes histogram building HBM-friendly.

Bin semantics match LightGBM's: bin b holds values x <= upper_bound[b], the last
bin is +inf. NaN maps to the LAST bin of each feature (missing treated as
largest — LightGBM's default missing-value direction with `use_missing` and
`zero_as_missing=False`).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class BinMapper(NamedTuple):
    """Per-feature binning decided on (a sample of) the training data."""
    upper_bounds: np.ndarray   # (n_features, max_bin) f32; +inf padded
    n_bins: np.ndarray         # (n_features,) actual bin count used
    max_bin: int
    # bool (n_features,) — True columns hold integer category ids and are
    # binned by IDENTITY (bin = clip(floor(x), 0, max_bin)); None = all
    # numeric (old artifacts). Reference: categoricalSlotIndexes,
    # lightgbm/params/LightGBMParams.scala:184-196.
    categorical: Optional[np.ndarray] = None

    @property
    def n_features(self) -> int:
        return self.upper_bounds.shape[0]

    def _cat_mask(self) -> np.ndarray:
        if self.categorical is None:
            return np.zeros(self.n_features, bool)
        return self.categorical


def fit_bins(x: np.ndarray, max_bin: int = 255,
             sample_cnt: int = 200_000, seed: int = 2,
             categorical_features=()) -> BinMapper:
    """Choose at most max_bin quantile boundaries per feature.

    LightGBM samples `bin_construct_sample_cnt` (default 200000) rows to find
    boundaries; we do the same so 1B-row tables don't need a full pass.

    `categorical_features` columns are identity-binned: the value IS the
    category id, clipped to [0, max_bin] (index categories by frequency —
    featurize's ValueIndexer does — so rare tails share the overflow bin).
    NaN maps to the last bin, like the numeric missing-value direction.
    """
    n, f = x.shape
    if n > sample_cnt:
        rng = np.random.default_rng(seed)
        x = x[rng.choice(n, sample_cnt, replace=False)]
    ubs = np.full((f, max_bin), np.inf, dtype=np.float32)
    nbins = np.zeros(f, dtype=np.int32)
    cat_mask = np.zeros(f, dtype=bool)
    if len(categorical_features):
        cat_mask[np.asarray(categorical_features, int)] = True
    for j in range(f):
        if cat_mask[j]:
            # identity bins; boundaries at k + 0.5 keep even a cat-unaware
            # threshold consumer piecewise-consistent with the bin ids
            nbins[j] = max_bin + 1
            ubs[j] = np.arange(max_bin, dtype=np.float32) + 0.5
            continue
        col = x[:, j]
        col = col[~np.isnan(col)]
        uniq = np.unique(col)
        if uniq.size <= 1:
            nbins[j] = 1
            continue
        if uniq.size <= max_bin:
            # distinct-value bins: boundary = midpoint between neighbors
            bounds = (uniq[:-1] + uniq[1:]) / 2.0
        else:
            # max_bin+1 grid points -> max_bin-1 interior boundaries ->
            # a full max_bin bins (was off by one before)
            qs = np.linspace(0, 1, max_bin + 1)[1:-1]
            bounds = np.unique(np.quantile(col, qs))
        k = min(bounds.size, max_bin - 1)
        ubs[j, :k] = bounds[:k]
        ubs[j, k:] = np.inf
        nbins[j] = k + 1
    return BinMapper(upper_bounds=ubs, n_bins=nbins, max_bin=max_bin,
                     categorical=cat_mask if cat_mask.any() else None)


def apply_bins(mapper: BinMapper, x: np.ndarray) -> np.ndarray:
    """Vectorized bin assignment: (n_rows, n_features) -> uint8 bins.

    bin = searchsorted(upper_bounds, x, 'left'): value <= ub[b] lands in b.
    NaN lands in the last bin of each feature (treated as largest, matching
    LightGBM's default missing handling direction).
    """
    n, f = x.shape
    out = np.empty((n, f), dtype=np.uint8)
    for j in range(f):
        k = int(mapper.n_bins[j])
        b = np.searchsorted(mapper.upper_bounds[j, : max(k - 1, 0)], x[:, j],
                            side="left")
        b = np.where(np.isnan(x[:, j]), k - 1, b)
        out[:, j] = b.astype(np.uint8)
    return out


def bin_threshold_value(mapper: BinMapper, feature: int, bin_id: int) -> float:
    """Real-valued decision threshold for 'go left if bin <= bin_id'."""
    return float(mapper.upper_bounds[feature, bin_id])


_assign_bins_jit = None


def _get_assign_bins():
    """Module-level jitted assigner so repeated fits hit the jit cache
    (a per-call closure would retrace + recompile every training run)."""
    global _assign_bins_jit
    if _assign_bins_jit is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _assign(ub, nb, xd):
            def one_feature(ub_j, nb_j, col):
                b = jnp.searchsorted(ub_j, col, side="left")
                b = jnp.where(jnp.isnan(col), nb_j - 1, b)
                return jnp.minimum(b, nb_j - 1)
            out = jax.vmap(one_feature, in_axes=(0, 0, 1), out_axes=1)(ub, nb, xd)
            return out.astype(jnp.uint8)

        _assign_bins_jit = _assign
    return _assign_bins_jit


def apply_bins_device(mapper: BinMapper, x):
    """Device-side bin assignment: one jitted vmapped searchsorted instead of
    a host loop (the host path costs ~6s at 1M x 32; this is milliseconds on
    TPU and keeps the bins matrix on-device for training)."""
    import jax.numpy as jnp
    return _get_assign_bins()(jnp.asarray(mapper.upper_bounds),
                              jnp.asarray(mapper.n_bins),
                              jnp.asarray(x, jnp.float32))
