"""TPU compute ops.

API-level gradient contract for `flash_attention_stats` (ops.flash_attention):
its flash VJP drops the `m` cotangent, so gradients are exact ONLY for
shift-invariant consumers of (acc, m, l) — ones unchanged under
(acc e^{-d}, m + d, l e^{-d}), which the ring-attention merge satisfies.
A consumer that differentiates a non-shift-invariant readout of the raw
stats silently gets wrong gradients; set
`flash_attention.DEBUG_STATS_EXACT_VJP = True` to route gradients through
the dense XLA reference (exact for ALL consumers, O(S^2) memory) and
compare. The flag is read at TRACE time — flip it before building the
jitted function you compare (an already-compiled function keeps the flash
path). `flash_attention` itself (the normalized entry point) is exact for
every consumer.
"""
from .binning import BinMapper, fit_bins, apply_bins, bin_threshold_value
from .histogram import node_feature_histograms

__all__ = ["BinMapper", "fit_bins", "apply_bins", "bin_threshold_value",
           "flash_attention", "node_feature_histograms"]


def __getattr__(name):
    # lazy: flash_attention pulls in pallas; binning/hashing consumers on
    # CPU-only paths must not pay that import
    if name == "flash_attention":
        from .flash_attention import flash_attention
        return flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
