from .binning import BinMapper, fit_bins, apply_bins, bin_threshold_value
from .histogram import node_feature_histograms

__all__ = ["BinMapper", "fit_bins", "apply_bins", "bin_threshold_value",
           "node_feature_histograms"]
