from .binning import BinMapper, fit_bins, apply_bins, bin_threshold_value
from .histogram import node_feature_histograms

__all__ = ["BinMapper", "fit_bins", "apply_bins", "bin_threshold_value",
           "flash_attention", "node_feature_histograms"]


def __getattr__(name):
    # lazy: flash_attention pulls in pallas; binning/hashing consumers on
    # CPU-only paths must not pay that import
    if name == "flash_attention":
        from .flash_attention import flash_attention
        return flash_attention
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
