"""Static-width sparse feature pairs: the framework-wide sparse convention.

A sparse feature column is a PAIR of dense arrays `<name>_idx` (n, W) int32
and `<name>_val` (n, W) f32 with a schema-static width W; empty slots carry
val 0 (their idx is irrelevant). This replaces Spark's boxed SparseVector
(reference: featurize/Featurize.scala's hashing output, text
TextFeaturizer's HashingTF vectors) with a shape XLA can consume directly:
scatter/segment-sum over idx, no ragged rows, no host boxing. The VW
learner's segment-sum SGD (models/vw/learner.py) consumes exactly this.
"""
from __future__ import annotations

import numpy as np

# policy shared by every featurizer with a dense/sparse auto switch: widths
# above this emit sparse pair columns under dense_output='auto'
DENSE_AUTO_LIMIT = 1 << 14


def _densify(i, v, width):
    import jax.numpy as jnp
    n = i.shape[0]
    out = jnp.zeros((n, width), jnp.float32)
    rows = jnp.broadcast_to(jnp.arange(n)[:, None], i.shape)
    return out.at[rows, i].add(v)


_densify_jit = None  # module-level jit: one compile per (shape, width)


def to_dense(idx: np.ndarray, val: np.ndarray, width: int) -> np.ndarray:
    """(n, W) sparse pair -> (n, width) dense f32, summing collisions.
    Device-side segment-sum; use only when width is small enough to hold."""
    import jax
    import jax.numpy as jnp
    global _densify_jit
    if _densify_jit is None:
        _densify_jit = jax.jit(_densify, static_argnames=("width",))
    return np.asarray(_densify_jit(jnp.asarray(idx, jnp.int32),
                                   jnp.asarray(val, jnp.float32), int(width)))


def rows_to_pair(rows_idx, rows_val, min_width: int = 1):
    """Ragged per-row (indices, values) lists -> padded (n, W) pair."""
    n = len(rows_idx)
    width = max(max((len(r) for r in rows_idx), default=0), min_width)
    idx = np.zeros((n, width), np.int32)
    val = np.zeros((n, width), np.float32)
    for i, (ri, rv) in enumerate(zip(rows_idx, rows_val)):
        k = len(ri)
        idx[i, :k] = ri
        val[i, :k] = rv
    return idx, val
