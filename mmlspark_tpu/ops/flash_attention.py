"""Pallas TPU flash attention: exact attention in O(block) VMEM.

Within-chip complement of the cross-chip sequence parallelism in
parallel/ring_attention.py (SURVEY.md §5 — long context is first-class in
the TPU build; the reference has no attention ops at all). The ring handles
sequences sharded ACROSS devices; this kernel handles a long block WITHIN a
device without materializing the (S, S) score matrix in HBM:

    grid = (heads, q_blocks, k_blocks), k innermost. Each (h, qb) cell
    streams k-blocks through VMEM keeping the classic online-softmax
    carry (running max m, denominator l, unnormalized accumulator acc) in
    scratch; the normalized output is written once at the last k step.

Causal masking compares global q/k positions, so it works for any block
shape. Training: a custom VJP recomputes attention with the XLA reference
path on the backward (O(S^2) memory there — flash backward is a later
optimization), keeping forward inference/serving memory flat.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_Q = 256
BLOCK_K = 256


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  n_k: int, block_q: int, block_k: int, seq_end,
                  causal: bool, scale: float, q_offset=0,
                  k_offset=0, m_out_ref=None, l_out_ref=None,
                  normalize: bool = True):
    # q_offset/k_offset/seq_end may be static ints or traced SMEM scalars
    # (ring attention's per-device offsets come from axis_index)
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: a k-block wholly above the diagonal contributes nothing —
    # skip its matmuls entirely (halves causal compute; DMA still streams
    # the block, which is bandwidth-trivial next to the MXU work)
    visible = (not causal) or (k_offset + kb * block_k
                               <= q_offset + qb * block_q + block_q - 1)

    @pl.when(visible)
    def _attend():
        # note: the f32 casts here are what Mosaic wants — it fuses them
        # into the matmul; bf16 and f32 operands measure within tunnel noise
        # of each other (~24-27 ms at 16k causal on v5e, BENCH_MODE=flash);
        # keeping operands in input dtype with post-scale measured SLOWER.
        # Accumulation stays f32 either way.
        q = q_ref[0].astype(jnp.float32) * scale      # (Bq, D)
        k = k_ref[0].astype(jnp.float32)              # (Bk, D)
        v = v_ref[0].astype(jnp.float32)              # (Bk, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)

        q_pos = q_offset + qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = k_offset + kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_pos < seq_end                       # padded keys drop out
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, -1e30)

        m_prev = m_ref[...]                           # (Bq, 1)
        l_prev = l_ref[...]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        # NOTE: p is deliberately NOT masked with `valid` here — an extra
        # where on the (Bq, Bk) tile adds measurable inner-loop VPU work at
        # zero benefit for supported callers. The only rows affected are
        # ones that have seen NO valid key yet (m_new still -1e30, masked
        # entries contribute exp(0)=1): impossible on the normalize path
        # (causal row i always sees key 0; padding only trims the tail),
        # and on the stats path such rows are FLAGGED by m == -1e30 — the
        # ring consumer's merge weight exp(m - m_new) zeroes them. Direct
        # flash_attention_stats callers must treat m == -1e30 rows as
        # "no visible keys" rather than normalizing acc/l.
        p = jnp.exp(s - m_new)                        # (Bq, Bk)
        alpha = jnp.exp(m_prev - m_new)               # rescale old carry
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha
                        + jax.lax.dot_general(
                            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(kb == n_k - 1)
    def _finish():
        if normalize:
            o_ref[0] = (acc_ref[...]
                        / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)
        else:  # stats mode: unnormalized accumulator + carry for merging
            o_ref[0] = acc_ref[...].astype(o_ref.dtype)
        if m_out_ref is not None:
            m_out_ref[0] = m_ref[...]
            l_out_ref[0] = l_ref[...]


def _pad_blocks(q, k, v, block_q: int, block_k: int):
    """Pad (H, S, D) operands up to block multiples; returns the padded
    arrays + (s, sk, n_q, n_k). One implementation for both entry points so
    padding/grid logic can never diverge."""
    h, s, d = q.shape
    sk = k.shape[1]
    pad_q = (-s) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    return q, k, v, s, sk, (s + pad_q) // block_q, (sk + pad_k) // block_k


_COMPILER_PARAMS = None


def _compiler_params():
    global _COMPILER_PARAMS
    if _COMPILER_PARAMS is None:
        _COMPILER_PARAMS = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return _COMPILER_PARAMS


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    """(H, S, D) per-head layout in, (H, S, D) out."""
    d = q.shape[-1]
    h = q.shape[0]
    q, k, v, s, sk, n_q, n_k = _pad_blocks(q, k, v, block_q, block_k)

    kernel = functools.partial(
        _flash_kernel, n_k=n_k, block_q=block_q, block_k=block_k,
        seq_end=sk, causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda hh, qb, kb: (hh, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((h, q.shape[1], d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(q, k, v)
    return out[:, :s]


def flash_attention_stats(q, k, v, q_offset, k_offset, causal: bool,
                          scale: float, block_q: int = BLOCK_Q,
                          block_k: int = BLOCK_K,
                          interpret: Optional[bool] = None):
    """Streaming-softmax PARTIAL attention for one K/V block: returns the
    UNNORMALIZED accumulator plus the (m, l) carry, in the shapes ring
    attention merges — acc (S, H, D) f32, m/l (H, S). q_offset/k_offset are
    the blocks' global positions (causal masking across shards; traced
    values welcome — they enter the kernel through SMEM). Differentiable:
    the custom VJP recomputes the same contract densely in XLA on the
    backward, like flash_attention. This is what lets ring attention run
    flash WITHIN each device while `ppermute` rotates K/V ACROSS devices.

    CONTRACT (tested in test_flash_attention.py::test_stats_no_visible_key
    _contract): a q row with NO visible key in this block (causal offsets)
    returns garbage acc/l FLAGGED by m == -1e30 — consumers must fold such
    rows with zero weight (the ring merge's exp(m - m_new) does exactly
    that) instead of normalizing acc/l directly. Masking them inside the
    kernel would add inner-loop VPU work on every tile to benefit only
    this degenerate case (see the p computation note)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _flash_stats_vjp(q, k, v,
                            jnp.asarray(q_offset, jnp.int32),
                            jnp.asarray(k_offset, jnp.int32),
                            bool(causal), float(scale), int(block_q),
                            int(block_k), bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_stats_vjp(q, k, v, q_offset, k_offset, causal, scale, block_q,
                     block_k, interpret):
    return _flash_stats_forward(q, k, v, q_offset, k_offset, causal, scale,
                                block_q, block_k, interpret)


def _stats_xla_reference(q, k, v, q_offset, k_offset, causal, scale):
    """Dense XLA implementation of the stats contract (backward pass)."""
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    q_pos = q_offset + jnp.arange(q.shape[0])
    k_pos = k_offset + jnp.arange(k.shape[0])
    if causal:
        s = jnp.where((q_pos[:, None] >= k_pos[None, :])[None], s, -1e30)
    m = jnp.maximum(jnp.max(s, axis=-1), -1e30)             # (H, S)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
    return acc, m, l


def _flash_stats_fwd(q, k, v, q_offset, k_offset, causal, scale, block_q,
                     block_k, interpret):
    out = _flash_stats_forward(q, k, v, q_offset, k_offset, causal, scale,
                               block_q, block_k, interpret)
    return out, (q, k, v, q_offset, k_offset)


def _flash_stats_bwd(causal, scale, block_q, block_k, interpret, res, g):
    import jax.dtypes
    q, k, v, q_offset, k_offset = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _stats_xla_reference(q_, k_, v_, q_offset,
                                                k_offset, causal, scale),
        q, k, v)
    dq, dk, dv = vjp(g)
    zero_int = np.zeros((), jax.dtypes.float0)
    return dq, dk, dv, zero_int, zero_int


_flash_stats_vjp.defvjp(_flash_stats_fwd, _flash_stats_bwd)


def _flash_stats_forward(q, k, v, q_offset, k_offset, causal, scale,
                         block_q, block_k, interpret):
    qh = jnp.moveaxis(q, 1, 0)   # (H, S, D)
    kh = jnp.moveaxis(k, 1, 0)
    vh = jnp.moveaxis(v, 1, 0)
    h, _, d = qh.shape
    qh, kh, vh, s, sk, n_q, n_k = _pad_blocks(qh, kh, vh, block_q, block_k)

    def kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, m_o, l_o,
               acc_ref, m_ref, l_ref):
        qoff = qoff_ref[0]
        koff = koff_ref[0]
        _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                      n_k=n_k, block_q=block_q, block_k=block_k,
                      seq_end=koff + sk, causal=causal, scale=scale,
                      q_offset=qoff, k_offset=koff,
                      m_out_ref=m_o, l_out_ref=l_o, normalize=False)

    qoff_arr = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff_arr = jnp.asarray(k_offset, jnp.int32).reshape(1)
    acc, m, l = pl.pallas_call(
        kernel,
        grid=(h, n_q, n_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
            pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_q, 1), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_q, 1), lambda hh, qb, kb: (hh, qb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, qh.shape[1], d), jnp.float32),
            jax.ShapeDtypeStruct((h, qh.shape[1], 1), jnp.float32),
            jax.ShapeDtypeStruct((h, qh.shape[1], 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qoff_arr, koff_arr, qh, kh, vh)
    # ring-merge shapes: acc (S, H, D), m/l (H, S)
    return (jnp.moveaxis(acc[:, :s], 0, 1), m[:, :s, 0], l[:, :s, 0])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_shd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _xla_reference_shd(q, k, v, causal, scale):
    s = jnp.einsum("hqd,hkd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        qp = jnp.arange(q.shape[1])[:, None]
        kp = jnp.arange(k.shape[1])[None, :]
        s = jnp.where((qp >= kp)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,hkd->hqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _flash_fwd_vjp(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret), (q, k, v)


def _flash_bwd_vjp(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_reference_shd(q_, k_, v_, causal, scale),
        q, k, v)
    return vjp(g)


_flash_shd.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                    interpret: Optional[bool] = None):
    """Exact attention without the (S, S) HBM score matrix.

    q: (S, H, D); k/v: (Sk, H, D). Returns (S, H, D), same dtype as q.
    `interpret` defaults to True off-TPU so tests run anywhere.
    """
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    qh = jnp.moveaxis(jnp.asarray(q), 1, 0)   # (H, S, D)
    kh = jnp.moveaxis(jnp.asarray(k), 1, 0)
    vh = jnp.moveaxis(jnp.asarray(v), 1, 0)
    out = _flash_shd(qh, kh, vh, bool(causal), float(scale), int(block_q),
                     int(block_k), bool(interpret))
    return jnp.moveaxis(out, 0, 1)
