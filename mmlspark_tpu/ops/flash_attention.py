"""Pallas TPU flash attention: exact attention in O(block) VMEM.

Within-chip complement of the cross-chip sequence parallelism in
parallel/ring_attention.py (SURVEY.md §5 — long context is first-class in
the TPU build; the reference has no attention ops at all). The ring handles
sequences sharded ACROSS devices; this kernel handles a long block WITHIN a
device without materializing the (S, S) score matrix in HBM:

    grid = (heads, q_blocks, k_blocks), k innermost. Each (h, qb) cell
    streams k-blocks through VMEM keeping the classic online-softmax
    carry (running max m, denominator l, unnormalized accumulator acc) in
    scratch; the normalized output is written once at the last k step.

Causal masking compares global q/k positions, so it works for any block
shape. Training: `flash_attention`'s custom VJP is a FLASH BACKWARD — two
Pallas kernels (dq over a (h, qb, kb) grid; dk/dv over (h, kb, qb))
recompute each P block from q/k and the forward's saved log-sum-exp, so
backward memory stays O(block) like the forward. Measured on v5e at 16k
causal (BENCH_MODE=flash, 25-rep in-graph timing, round 5): bf16 forward
d=64 8.2 ms = 5.0x dense XLA (33.5 TFLOP/s — the D=64 head dim caps the
MXU at half its array, ~98 TFLOP/s shape ceiling); d=128 8.3 ms =
66.2 TFLOP/s = 33.6% of chip bf16 peak (same wall time, twice the FLOPs —
the 128-lane contraction fully fed); fwd+bwd 20.9 ms either dim (92
TFLOP/s combined at d128) where the dense backward needs 17+ GB of score
gradients and OOMs. Perf notes: per-grid-cell overhead dominates below
1024-wide blocks (see _auto_blocks); 1024x1024 is also the d128 optimum
(2048-wide blocks fail VMEM compile at d128; 1024x2048 measured 8.39 ms
— no win); interior blocks skip all mask work; matmuls run in the input
dtype. `flash_attention_stats`' VJP is ALSO flash (the same two kernels
with lse := m and dsum := -dl — see _flash_stats_bwd's shift-invariance
derivation), so context-parallel ring training is O(block) memory in
both directions.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams/TPUMemorySpace to CompilerParams/MemorySpace;
# resolve whichever this jax ships so both sides of the rename run
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_MemorySpace = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace

BLOCK_Q = 256
BLOCK_K = 256
# Measured on v5e (16k causal, H=8 D=64, 25-rep in-graph timing): the
# kernel is per-grid-cell-overhead-bound at small blocks — 256x256 runs
# 24 ms forward, 1024x1024 runs 8.5 ms (and 21 ms fwd+bwd vs 59 ms).
# 2048+ blocks fail to compile (VMEM); the f32 BACKWARD also fails at
# 1024 (f32 operand blocks double the VMEM footprint), so the backward
# caps at 512 for f32. _auto_blocks picks these per call.
_FWD_BLOCK = 1024
_BWD_BLOCK_BF16 = 1024
_BWD_BLOCK_F32 = 512


def _pick_block(seq: int) -> int:
    """Largest block in {1024, 512, 256} whose padding waste stays under
    20% of the padded length — big blocks win on grid-cell overhead for
    long sequences, but an S=1100 sequence must not pad to 2048 (the
    overhead problem they solve only exists when the grid is large)."""
    for b in (_FWD_BLOCK, _FWD_BLOCK // 2, BLOCK_Q):
        pad = (-seq) % b
        if pad * 5 <= seq + pad:
            return b
    return BLOCK_Q


def _auto_blocks(seq_q: int, seq_k: int, dtype) -> tuple:
    """(block_q, block_k, bwd_block_q, bwd_block_k) for this shape/dtype.
    Per-dim waste-bounded block choice; the backward uses smaller blocks
    for f32 (VMEM)."""
    bq = _pick_block(seq_q)
    bk = _pick_block(seq_k)
    bwd_cap = (_BWD_BLOCK_BF16 if jnp.dtype(dtype) == jnp.bfloat16
               else _BWD_BLOCK_F32)
    return bq, bk, min(bq, bwd_cap), min(bk, bwd_cap)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                  n_k: int, block_q: int, block_k: int, seq_end,
                  causal: bool, scale: float, q_offset=0,
                  k_offset=0, m_out_ref=None, l_out_ref=None,
                  normalize: bool = True):
    # q_offset/k_offset/seq_end may be static ints or traced SMEM scalars
    # (ring attention's per-device offsets come from axis_index)
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: a k-block wholly above the diagonal contributes nothing —
    # skip its matmuls entirely (halves causal compute; DMA still streams
    # the block, which is bandwidth-trivial next to the MXU work)
    visible = (not causal) or (k_offset + kb * block_k
                               <= q_offset + qb * block_q + block_q - 1)
    # a block needing NO mask at all: every key is < seq_end and (causal)
    # every q_pos >= k_pos. Interior blocks take the maskless branch —
    # the iota/compare/where passes over the (Bq, Bk) tile are pure VPU
    # overhead that only boundary blocks need (at D=64 the kernel is
    # VPU-bound, so this is a large fraction of inner-loop time)
    full = k_offset + (kb + 1) * block_k <= seq_end
    if causal:
        full = full & (k_offset + (kb + 1) * block_k - 1
                       <= q_offset + qb * block_q)

    def _attend(masked: bool):
        # matmuls run in the INPUT dtype with f32 accumulation
        # (preferred_element_type): bf16 operands use the MXU's full bf16
        # rate (~4x the f32 rate on v5e) and softmax/l/m math stays f32.
        q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)   # (Bq, D)
        k = k_ref[0]                                     # (Bk, D)
        v = v_ref[0]                                     # (Bk, D)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if masked:
            # sublane/lane iotas broadcast in the compare: no (Bq, Bk)
            # iota materialization
            q_pos = q_offset + qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = k_offset + kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            valid = k_pos < seq_end                   # padded keys drop out
            if causal:
                valid = valid & (q_pos >= k_pos)
            s = jnp.where(valid, s, -1e30)

        m_prev = m_ref[...]                           # (Bq, 1)
        l_prev = l_ref[...]
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        # NOTE: p is deliberately NOT masked with `valid` here — an extra
        # where on the (Bq, Bk) tile adds measurable inner-loop VPU work at
        # zero benefit for supported callers. The only rows affected are
        # ones that have seen NO valid key yet (m_new still -1e30, masked
        # entries contribute exp(0)=1): impossible on the normalize path
        # (causal row i always sees key 0; padding only trims the tail),
        # and on the stats path such rows are FLAGGED by m == -1e30 — the
        # ring consumer's merge weight exp(m - m_new) zeroes them. Direct
        # flash_attention_stats callers must treat m == -1e30 rows as
        # "no visible keys" rather than normalizing acc/l.
        p = jnp.exp(s - m_new)                        # (Bq, Bk)
        alpha = jnp.exp(m_prev - m_new)               # rescale old carry
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha
                        + jax.lax.dot_general(
                            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = m_new
        l_ref[...] = l_new

    @pl.when(full)
    def _attend_full():
        _attend(masked=False)

    @pl.when(visible & jnp.logical_not(full))
    def _attend_masked():
        _attend(masked=True)

    @pl.when(kb == n_k - 1)
    def _finish():
        if normalize:
            o_ref[0] = (acc_ref[...]
                        / jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)
        else:  # stats mode: unnormalized accumulator + carry for merging
            o_ref[0] = acc_ref[...].astype(o_ref.dtype)
        if m_out_ref is not None:
            m_out_ref[0] = m_ref[...]
            l_out_ref[0] = l_ref[...]


def _pad_blocks(q, k, v, block_q: int, block_k: int):
    """Pad (H, S, D) operands up to block multiples; returns the padded
    arrays + (s, sk, n_q, n_k). One implementation for both entry points so
    padding/grid logic can never diverge."""
    h, s, d = q.shape
    sk = k.shape[1]
    pad_q = (-s) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    return q, k, v, s, sk, (s + pad_q) // block_q, (sk + pad_k) // block_k


_COMPILER_PARAMS = None


def _compiler_params():
    global _COMPILER_PARAMS
    if _COMPILER_PARAMS is None:
        _COMPILER_PARAMS = _CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return _COMPILER_PARAMS


def _flash_forward(q, k, v, causal: bool, scale: float, block_q: int,
                   block_k: int, interpret: bool):
    """(H, S, D) per-head layout in, (H, S, D) out. Delegates to the
    LSE-emitting variant (two (H, S, 1) extra outputs are noise next to the
    O itself) so there is exactly ONE pallas_call configuration for the
    normalized forward — the forward and its VJP can never diverge."""
    out, _ = _flash_forward_lse(q, k, v, causal, scale, block_q, block_k,
                                interpret)
    return out


def flash_attention_stats(q, k, v, q_offset, k_offset, causal: bool,
                          scale: float, block_q: Optional[int] = None,
                          block_k: Optional[int] = None,
                          interpret: Optional[bool] = None):
    """Streaming-softmax PARTIAL attention for one K/V block: returns the
    UNNORMALIZED accumulator plus the (m, l) carry, in the shapes ring
    attention merges — acc (S, H, D) f32, m/l (H, S). q_offset/k_offset are
    the blocks' global positions (causal masking across shards; traced
    values welcome — they enter the kernel through SMEM). Differentiable
    with a FLASH backward (O(block) memory — see _flash_stats_bwd; exact
    for shift-invariant consumers like the ring merge). This is what lets
    ring attention run flash WITHIN each device while `ppermute` rotates
    K/V ACROSS devices, in both training directions.

    CONTRACT (tested in test_flash_attention.py::test_stats_no_visible_key
    _contract): a q row with NO visible key in this block (causal offsets)
    returns garbage acc/l FLAGGED by m == -1e30 — consumers must fold such
    rows with zero weight (the ring merge's exp(m - m_new) does exactly
    that) instead of normalizing acc/l directly. Masking them inside the
    kernel would add inner-loop VPU work on every tile to benefit only
    this degenerate case (see the p computation note)."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    a_bq, a_bk, _, _ = _auto_blocks(q.shape[0], k.shape[0], q.dtype)
    return _flash_stats_vjp(q, k, v,
                            jnp.asarray(q_offset, jnp.int32),
                            jnp.asarray(k_offset, jnp.int32),
                            bool(causal), float(scale),
                            int(block_q) if block_q is not None else a_bq,
                            int(block_k) if block_k is not None else a_bk,
                            bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_stats_vjp(q, k, v, q_offset, k_offset, causal, scale, block_q,
                     block_k, interpret):
    return _flash_stats_forward(q, k, v, q_offset, k_offset, causal, scale,
                                block_q, block_k, interpret)


def _stats_xla_reference(q, k, v, q_offset, k_offset, causal, scale):
    """Dense XLA implementation of the stats contract (backward pass)."""
    s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    q_pos = q_offset + jnp.arange(q.shape[0])
    k_pos = k_offset + jnp.arange(k.shape[0])
    if causal:
        s = jnp.where((q_pos[:, None] >= k_pos[None, :])[None], s, -1e30)
    m = jnp.maximum(jnp.max(s, axis=-1), -1e30)             # (H, S)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
    return acc, m, l


# Debug escape hatch for the shift-invariance gradient contract (see
# _flash_stats_bwd and the ops package docstring): when True, stats
# gradients route through the dense XLA reference VJP — exact for ALL
# consumers including non-shift-invariant readouts of (acc, m, l), at
# O(S^2) memory. Flip it to verify a new consumer's gradients match the
# flash path before trusting the O(block) backward.
# TRACE-TIME flag: it is read when the backward is traced, so a jitted
# function compiled before the flip keeps the flash path — flip it BEFORE
# building the jit (or call jax.clear_caches()); comparing two calls of
# one already-compiled function compares the flash path against itself.
DEBUG_STATS_EXACT_VJP = False


def _flash_stats_fwd(q, k, v, q_offset, k_offset, causal, scale, block_q,
                     block_k, interpret):
    out = _flash_stats_forward(q, k, v, q_offset, k_offset, causal, scale,
                               block_q, block_k, interpret)
    # the running max m is the only extra residual the flash backward
    # needs (it is the stats path's "lse")
    return out, (q, k, v, q_offset, k_offset, out[1])


def _flash_stats_bwd(causal, scale, block_q, block_k, interpret, res, g):
    """FLASH backward for the stats contract — O(block) memory in both
    directions (round-3 verdict item 4; the old implementation rebuilt the
    dense per-block P matrix, capping per-device sequence length exactly
    where context parallelism exists).

    Derivation: stats returns (acc, m, l) with acc_i = sum_j e^{s_ij-m_i}
    v_j, l_i = sum_j e^{s_ij-m_i}. Any SHIFT-INVARIANT consumer G — one
    with G(acc e^{-d}, m+d, l e^{-d}) = G(acc, m, l), which the ring merge
    satisfies (its weights e^{m-m_new} cancel the reference shift) — obeys
    the identity -da.acc + dm - dl*l = 0, which exactly cancels the argmax
    subgradient terms. What remains is ds_ij = p_ij (da_i.v_j + dl_i):
    the SAME recurrence as the normalized backward with lse := m and
    dsum := -dl, so both paths share the two Pallas kernels. The dm
    cotangent is consumed by that identity (non-shift-invariant consumers
    of m are outside the contract, like direct normalizers of flagged
    rows)."""
    import jax.dtypes
    q, k, v, q_offset, k_offset, m = res
    if DEBUG_STATS_EXACT_VJP:
        # exact-for-all-consumers reference path: differentiates the dense
        # stats (including the m cotangent) so a new consumer can check
        # its gradients against the flash path (ops package docstring)
        zero = np.zeros((), jax.dtypes.float0)
        _, ref_vjp = jax.vjp(
            lambda qq, kk, vv: _stats_xla_reference(
                qq, kk, vv, q_offset, k_offset, causal, scale), q, k, v)
        dq, dk, dv = ref_vjp(tuple(x.astype(jnp.float32) for x in g))
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
                zero, zero)
    qh = jnp.moveaxis(q, 1, 0)    # (H, S, D)
    kh = jnp.moveaxis(k, 1, 0)
    vh = jnp.moveaxis(v, 1, 0)
    d_acc, _d_m, d_l = g
    da_h = jnp.moveaxis(d_acc.astype(jnp.float32), 1, 0)      # (H, S, D)
    m3 = m[..., None]                                         # (H, S, 1)
    dsum = -d_l[..., None].astype(jnp.float32)                # (H, S, 1)
    # the backward caps its blocks by dtype (f32 operand blocks exceed
    # VMEM at 1024 — same caps as _auto_blocks)
    cap = (_BWD_BLOCK_BF16 if jnp.dtype(q.dtype) == jnp.bfloat16
           else _BWD_BLOCK_F32)
    dq, dk, dv = _flash_backward(
        qh, kh, vh, None, m3, da_h, causal, scale,
        min(block_q, cap), min(block_k, cap), interpret, dsum=dsum,
        q_offset=q_offset, k_offset=k_offset)
    zero_int = np.zeros((), jax.dtypes.float0)
    return (jnp.moveaxis(dq, 0, 1).astype(q.dtype),
            jnp.moveaxis(dk, 0, 1).astype(k.dtype),
            jnp.moveaxis(dv, 0, 1).astype(v.dtype),
            zero_int, zero_int)


_flash_stats_vjp.defvjp(_flash_stats_fwd, _flash_stats_bwd)


def _flash_stats_forward(q, k, v, q_offset, k_offset, causal, scale,
                         block_q, block_k, interpret):
    qh = jnp.moveaxis(q, 1, 0)   # (H, S, D)
    kh = jnp.moveaxis(k, 1, 0)
    vh = jnp.moveaxis(v, 1, 0)
    h, _, d = qh.shape
    qh, kh, vh, s, sk, n_q, n_k = _pad_blocks(qh, kh, vh, block_q, block_k)

    def kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, m_o, l_o,
               acc_ref, m_ref, l_ref):
        qoff = qoff_ref[0]
        koff = koff_ref[0]
        _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                      n_k=n_k, block_q=block_q, block_k=block_k,
                      seq_end=koff + sk, causal=causal, scale=scale,
                      q_offset=qoff, k_offset=koff,
                      m_out_ref=m_o, l_out_ref=l_o, normalize=False)

    qoff_arr = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff_arr = jnp.asarray(k_offset, jnp.int32).reshape(1)
    acc, m, l = pl.pallas_call(
        kernel,
        grid=(h, n_q, n_k),
        in_specs=[
            pl.BlockSpec(memory_space=_MemorySpace.SMEM),
            pl.BlockSpec(memory_space=_MemorySpace.SMEM),
            pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_q, 1), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_q, 1), lambda hh, qb, kb: (hh, qb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, qh.shape[1], d), jnp.float32),
            jax.ShapeDtypeStruct((h, qh.shape[1], 1), jnp.float32),
            jax.ShapeDtypeStruct((h, qh.shape[1], 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qoff_arr, koff_arr, qh, kh, vh)
    # ring-merge shapes: acc (S, H, D), m/l (H, S)
    return (jnp.moveaxis(acc[:, :s], 0, 1), m[:, :s, 0], l[:, :s, 0])


def _flash_forward_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    """Forward that ALSO returns the per-row log-sum-exp (H, S, 1) — the
    only extra residual the flash backward needs (FlashAttention's trick:
    P = exp(S - LSE) reconstructs the softmax block-by-block)."""
    d = q.shape[-1]
    h = q.shape[0]
    q, k, v, s, sk, n_q, n_k = _pad_blocks(q, k, v, block_q, block_k)

    def kernel(q_ref, k_ref, v_ref, o_ref, m_o, l_o, acc_ref, m_ref, l_ref):
        _flash_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
                      n_k=n_k, block_q=block_q, block_k=block_k,
                      seq_end=sk, causal=causal, scale=scale,
                      m_out_ref=m_o, l_out_ref=l_o, normalize=True)

    out, m, l = pl.pallas_call(
        kernel,
        grid=(h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_q, 1), lambda hh, qb, kb: (hh, qb, 0)),
            pl.BlockSpec((1, block_q, 1), lambda hh, qb, kb: (hh, qb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, q.shape[1], d), q.dtype),
            jax.ShapeDtypeStruct((h, q.shape[1], 1), jnp.float32),
            jax.ShapeDtypeStruct((h, q.shape[1], 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(q, k, v)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out[:, :s], lse[:, :s]


def _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, qb, kb, *,
                block_q: int, block_k: int, causal: bool, scale: float,
                k_end, q_offset, k_offset, masked: bool):
    """Recompute the (Bq, Bk) probability block and its dS — shared by both
    backward kernels so their masking/scaling can never diverge. Matmuls
    run in the input dtype with f32 accumulation (bf16 operands use the
    MXU's bf16 rate); `masked=False` skips the iota/compare/where passes on
    interior blocks, which only boundary blocks need. q_offset/k_offset/
    k_end may be static ints or traced SMEM scalars (the ring stats
    backward has per-device global offsets, like the forward)."""
    q = q_ref[0] * jnp.asarray(scale, q_ref.dtype)
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if masked:
        q_pos = q_offset + qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)
        k_pos = k_offset + kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = k_pos < k_end
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, -1e30)
    # padded q rows carry lse=+inf (set by the caller) -> p exactly 0
    p = jnp.exp(s - lse_ref[0])                       # (Bq, Bk)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - dsum_ref[0])                       # (Bq, Bk)
    return p, ds, do


def _flash_bwd_dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref,
                         lse_ref, dsum_ref, dq_ref, acc_ref, *, n_k: int,
                         block_q: int, block_k: int, causal: bool,
                         scale: float, k_end: int):
    qb, kb = pl.program_id(1), pl.program_id(2)
    qoff, koff = qoff_ref[0], koff_ref[0]

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _accum(masked: bool):
        _, ds, _ = _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref,
                               dsum_ref, qb, kb, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale,
                               k_end=koff + k_end, q_offset=qoff,
                               k_offset=koff, masked=masked)
        k = k_ref[0]
        acc_ref[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    full = _bwd_full_t(qb, kb, block_q, block_k, causal, k_end, qoff, koff)
    visible = _bwd_visible_t(qb, kb, block_q, block_k, causal, qoff, koff)

    @pl.when(full)
    def _accum_full():
        _accum(masked=False)

    @pl.when(visible & jnp.logical_not(full))
    def _accum_masked():
        _accum(masked=True)

    @pl.when(kb == n_k - 1)
    def _finish():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(qoff_ref, koff_ref, k_ref, v_ref, q_ref, do_ref,
                          lse_ref, dsum_ref, dk_ref, dv_ref, dk_acc,
                          dv_acc, *, n_q: int, block_q: int, block_k: int,
                          causal: bool, scale: float, k_end: int):
    kb, qb = pl.program_id(1), pl.program_id(2)
    qoff, koff = qoff_ref[0], koff_ref[0]

    @pl.when(qb == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _accum(masked: bool):
        p, ds, do = _bwd_common(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                dsum_ref, qb, kb, block_q=block_q,
                                block_k=block_k, causal=causal, scale=scale,
                                k_end=koff + k_end, q_offset=qoff,
                                k_offset=koff, masked=masked)
        q = q_ref[0]
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    full = _bwd_full_t(qb, kb, block_q, block_k, causal, k_end, qoff, koff)
    visible = _bwd_visible_t(qb, kb, block_q, block_k, causal, qoff, koff)

    @pl.when(full)
    def _accum_full():
        _accum(masked=False)

    @pl.when(visible & jnp.logical_not(full))
    def _accum_masked():
        _accum(masked=True)

    @pl.when(qb == n_q - 1)
    def _finish():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_visible_t(qb, kb, block_q: int, block_k: int, causal: bool,
                   q_offset=0, k_offset=0):
    """Traced block-visibility for the backward grids (same geometry as the
    forward's diagonal skip; offsets are the blocks' global positions on
    the ring stats path)."""
    if not causal:
        return qb >= 0   # always true, traced
    return (k_offset + kb * block_k
            <= q_offset + qb * block_q + block_q - 1)


def _bwd_full_t(qb, kb, block_q: int, block_k: int, causal: bool,
                k_end, q_offset=0, k_offset=0):
    """Traced no-mask-needed test for the backward grids (same geometry as
    the forward's `full`): every key < k_offset + k_end and, causal,
    wholly below the diagonal."""
    full = (kb + 1) * block_k <= k_end
    if causal:
        full = full & (k_offset + (kb + 1) * block_k - 1
                       <= q_offset + qb * block_q)
    return full


def _flash_backward(q, k, v, out, lse, g, causal, scale, block_q, block_k,
                    interpret, dsum=None, q_offset=0, k_offset=0):
    """(H, S, D) flash backward: dq via a (h, qb, kb) grid, dk/dv via a
    (h, kb, qb) grid — both recompute P block-wise from q/k and the saved
    LSE, so backward memory stays O(block) like the forward (the previous
    implementation re-ran dense XLA attention: O(S^2) HBM on backward,
    which forfeited the flash advantage exactly where training needs it).

    Two parameterizations share these kernels:
    - normalized attention: lse = log-sum-exp, dsum = rowsum(dO * O)
      (computed here when dsum is None);
    - ring STATS (flash_attention_stats' VJP): lse = the running max m,
      dsum = -dl, g = d_acc — algebraically the same ds = p*(dp - dsum)
      recurrence, see _flash_stats_bwd for the derivation. q_offset/
      k_offset are the blocks' global positions (traced scalars OK)."""
    d = q.shape[-1]
    h = q.shape[0]
    s_q = q.shape[1]
    sk = k.shape[1]
    q_p, k_p, v_p, _, _, n_q, n_k = _pad_blocks(q, k, v, block_q, block_k)
    pad_q = q_p.shape[1] - s_q
    g_p = jnp.pad(g, ((0, 0), (0, pad_q), (0, 0))) if pad_q else g
    if dsum is None:
        out_p = (jnp.pad(out, ((0, 0), (0, pad_q), (0, 0)))
                 if pad_q else out)
        # D = rowsum(dO * O); padded rows get LSE=+inf so every p block is 0
        dsum = jnp.sum(g_p.astype(jnp.float32) * out_p.astype(jnp.float32),
                       axis=-1, keepdims=True)                # (H, Sq, 1)
    elif pad_q:
        dsum = jnp.pad(dsum, ((0, 0), (0, pad_q), (0, 0)))
    lse_p = jnp.pad(lse, ((0, 0), (0, pad_q), (0, 0)),
                    constant_values=jnp.inf) if pad_q else lse
    qoff_arr = jnp.asarray(q_offset, jnp.int32).reshape(1)
    koff_arr = jnp.asarray(k_offset, jnp.int32).reshape(1)
    smem = pl.BlockSpec(memory_space=_MemorySpace.SMEM)

    row_spec_q = pl.BlockSpec((1, block_q, d), lambda hh, qb, kb: (hh, qb, 0))
    col_spec_k = pl.BlockSpec((1, block_k, d), lambda hh, qb, kb: (hh, kb, 0))
    one_spec_q = pl.BlockSpec((1, block_q, 1), lambda hh, qb, kb: (hh, qb, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, n_k=n_k, block_q=block_q,
                          block_k=block_k, causal=causal, scale=scale,
                          k_end=sk),
        grid=(h, n_q, n_k),
        in_specs=[smem, smem, row_spec_q, col_spec_k, col_spec_k,
                  row_spec_q, one_spec_q, one_spec_q],
        out_specs=row_spec_q,
        out_shape=jax.ShapeDtypeStruct(q_p.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qoff_arr, koff_arr, q_p, k_p, v_p, g_p, lse_p, dsum)[:, :s_q]

    # dk/dv grid: k-blocks outer, q-blocks inner (accumulated)
    row_spec_kb = pl.BlockSpec((1, block_k, d), lambda hh, kb, qb: (hh, kb, 0))
    col_spec_qb = pl.BlockSpec((1, block_q, d), lambda hh, kb, qb: (hh, qb, 0))
    one_spec_qb = pl.BlockSpec((1, block_q, 1), lambda hh, kb, qb: (hh, qb, 0))
    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, n_q=n_q, block_q=block_q, block_k=block_k,
        causal=causal, scale=scale, k_end=sk)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(h, n_k, n_q),
        in_specs=[smem, smem, row_spec_kb, row_spec_kb, col_spec_qb,
                  col_spec_qb, one_spec_qb, one_spec_qb],
        out_specs=[row_spec_kb, row_spec_kb],
        out_shape=[jax.ShapeDtypeStruct(k_p.shape, k.dtype),
                   jax.ShapeDtypeStruct(v_p.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(qoff_arr, koff_arr, k_p, v_p, q_p, g_p, lse_p, dsum)
    return dq, dk[:, :sk], dv[:, :sk]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_shd(q, k, v, causal, scale, block_q, block_k, bwd_block_q,
               bwd_block_k, interpret):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k,
                          interpret)


def _xla_reference_shd(q, k, v, causal, scale):
    s = jnp.einsum("hqd,hkd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        qp = jnp.arange(q.shape[1])[:, None]
        kp = jnp.arange(k.shape[1])[None, :]
        s = jnp.where((qp >= kp)[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,hkd->hqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _flash_fwd_vjp(q, k, v, causal, scale, block_q, block_k, bwd_block_q,
                   bwd_block_k, interpret):
    out, lse = _flash_forward_lse(q, k, v, causal, scale, block_q, block_k,
                                  interpret)
    return out, (q, k, v, out, lse)   # lse: (H, S, 1)


def _flash_bwd_vjp(causal, scale, block_q, block_k, bwd_block_q,
                   bwd_block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, scale, bwd_block_q,
                           bwd_block_k, interpret)


_flash_shd.defvjp(_flash_fwd_vjp, _flash_bwd_vjp)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Exact attention without the (S, S) HBM score matrix.

    q: (S, H, D); k/v: (Sk, H, D). Returns (S, H, D), same dtype as q.
    block_q/block_k default to a measured-on-v5e auto choice (1024 for
    long sequences; the BACKWARD internally caps at 512 for f32 operands,
    which exceed VMEM at 1024). `interpret` defaults to True off-TPU so
    tests run anywhere.
    """
    if scale is None:
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    q = jnp.asarray(q)
    a_bq, a_bk, a_bwd_bq, a_bwd_bk = _auto_blocks(
        q.shape[0], k.shape[0], q.dtype)
    bq = int(block_q) if block_q is not None else a_bq
    bk = int(block_k) if block_k is not None else a_bk
    # explicit blocks pin the backward too (sweep scripts rely on that) —
    # but capped by the dtype VMEM ceiling: an f32 caller passing
    # block_q=1024 would otherwise hit the documented f32-backward VMEM
    # compile failure only at grad time (round-4 advisor)
    bwd_cap = (_BWD_BLOCK_BF16 if jnp.dtype(q.dtype) == jnp.bfloat16
               else _BWD_BLOCK_F32)
    bwd_bq = min(int(block_q), bwd_cap) if block_q is not None else a_bwd_bq
    bwd_bk = min(int(block_k), bwd_cap) if block_k is not None else a_bwd_bk
    qh = jnp.moveaxis(q, 1, 0)                # (H, S, D)
    kh = jnp.moveaxis(jnp.asarray(k), 1, 0)
    vh = jnp.moveaxis(jnp.asarray(v), 1, 0)
    out = _flash_shd(qh, kh, vh, bool(causal), float(scale), bq, bk,
                     bwd_bq, bwd_bk, bool(interpret))
    return jnp.moveaxis(out, 0, 1)
