"""The GBDT hot op: per-(node, feature, bin) gradient/hessian/count histograms.

This is the TPU-native equivalent of LightGBM's C++ histogram construction
kernels (the work inside `LGBM_BoosterUpdateOneIter`, reference:
lightgbm/TrainUtils.scala:326-358 — SURVEY.md §2.9 item 1). Histogram build is
memory-bandwidth-shaped (scatter-add over binned features), not matmul-shaped;
the XLA path lowers to a single fused scatter-add via segment_sum over
composite keys. The Pallas TPU kernel family (histogram_pallas.py) keeps the
bins tile in VMEM and accumulates all three statistics in one pass; selection
is automatic by backend with an env escape hatch:

    MMLSPARK_TPU_HIST = auto | xla | pallas | planes

`planes` additionally makes fit_booster precompute the level-invariant lo
one-hot planes once per fit (build_hist_plan) and routes shallow levels
through the plane-streaming kernel — see the routing table and ledger at the
top of histogram_pallas.py. Every kernel-route selection is counted at trace
time (`gbdt.hist.route.<route>`), so a compile log shows which kernels a fit
actually instantiated.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..reliability.metrics import reliability_metrics
from ..telemetry import names as tnames


def _xla_hist(bins, grad, hess, node_local, active, n_nodes: int, n_bins: int,
              count_w=None):
    """One fused scatter-add: key = ((node * F) + f) * B + bin.

    Inactive rows get an out-of-range segment id and are dropped by XLA's
    scatter OOB semantics — the moral equivalent of the reference's 'ignore'
    ring members for empty partitions (TrainUtils.scala:577-580).
    """
    n, f = bins.shape
    num_segments = n_nodes * f * n_bins
    feat_ids = jnp.arange(f, dtype=jnp.int32)[None, :]
    keys = (node_local[:, None] * f + feat_ids) * n_bins + bins.astype(jnp.int32)
    keys = jnp.where(active[:, None], keys, num_segments)  # OOB -> dropped
    keys = keys.reshape(-1)

    def seg(vals):
        out = jax.ops.segment_sum(vals.reshape(-1), keys,
                                  num_segments=num_segments)
        return out.reshape(n_nodes, f, n_bins)

    # count histogram: count_w is the bagging/padding indicator (1 = row is
    # present this iteration, 0 = bagged-out / GOSS-dropped / distributed
    # padding). LightGBM removes such rows from data counts; user sample
    # weights do NOT change counts, so this must be an indicator, not hess.
    cnt = (jnp.ones_like(hess) if count_w is None
           else count_w.astype(jnp.float32))
    hg = seg(jnp.broadcast_to(grad[:, None], (n, f)))
    hh = seg(jnp.broadcast_to(hess[:, None], (n, f)))
    hc = seg(jnp.broadcast_to(cnt[:, None], (n, f)))
    return hg, hh, hc


def node_feature_histograms(bins, grad, hess, node_local, active,
                            n_nodes: int, n_bins: int, count_w=None,
                            lo_planes=None, plane_lo: int = 0):
    """(n,F) uint8 bins + per-row grad/hess -> three (n_nodes, F, n_bins) f32
    histograms. Rows with active=False contribute nothing; rows with
    count_w=0 contribute to no statistic's count (see _xla_hist).

    `lo_planes`/`plane_lo`: per-fit precomputed level-invariant one-hot
    planes (histogram_pallas.build_hist_plan) — routes shallow levels
    through the plane-streaming kernel when present."""
    impl = os.environ.get("MMLSPARK_TPU_HIST", "auto")
    use_pallas = (impl in ("pallas", "planes")
                  or (impl == "auto" and _should_use_pallas(n_nodes)))
    if use_pallas:
        try:
            from .histogram_pallas import kernel_route, pallas_hist
        except ImportError as e:
            if impl in ("pallas", "planes"):
                raise NotImplementedError(
                    f"MMLSPARK_TPU_HIST={impl} requested but the Pallas "
                    "histogram kernel failed to import; unset the env var to "
                    "use the XLA scatter path") from e
            use_pallas = False
    if use_pallas:
        has_planes = lo_planes is not None and plane_lo > 0
        kind, _lo = kernel_route(n_nodes, n_bins, has_planes=has_planes)
        # trace-time routing record: one count per compiled (m, B) kernel
        # instantiation — the compile-log view of which route a fit took
        reliability_metrics.inc(tnames.gbdt_hist_route(kind))
        return pallas_hist(bins, grad, hess, node_local, active, n_nodes,
                           n_bins, count_w=count_w,
                           lo_planes=lo_planes if has_planes else None,
                           plane_lo=plane_lo if has_planes else 0,
                           # interpreter escape hatch: exercises the REAL
                           # routed-kernel plumbing on the CPU backend
                           # (tier-1 end-to-end planes test; debugging)
                           interpret=os.environ.get(
                               "MMLSPARK_TPU_HIST_INTERPRET") == "1")
    reliability_metrics.inc(tnames.gbdt_hist_route("xla"))
    return _xla_hist(bins, grad, hess, node_local, active, n_nodes, n_bins,
                     count_w=count_w)


def _should_use_pallas(n_nodes: int) -> bool:
    """Pallas matmul-histogram on TPU (the XLA scatter is serialized there);
    the node-onehot trick is VMEM-bounded, so very deep levels fall back."""
    try:
        from .histogram_pallas import M_MAX
    except ImportError:
        return False
    if n_nodes > M_MAX:
        return False
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# --------------------------------------------------- semantic contract
# Registered in analysis/semantic/registry.py: the histogram build at a
# canonical routed shape. On CPU this lowers the XLA scatter route (the
# Pallas routes need a TPU) — degraded but non-vacuous: identity,
# host-sync, and the zero-collective budget still bind the program the
# tier-1 backend actually compiles.
from ..analysis.semantic import Case, hot_path_contract  # noqa: E402


@hot_path_contract(
    "gbdt.hist.kernel",
    expected_executables=1,
    donate_expected=(),
    collective_budget={},        # node-local histograms: the psum lives
                                 # in the distributed tree contract
)
def gbdt_hist_route_contract():
    import functools as _ft

    import jax.numpy as jnp
    import numpy as _np

    fn = _ft.partial(node_feature_histograms, n_nodes=8, n_bins=16)
    rng = _np.random.default_rng(0)

    def args():
        return (jnp.asarray(rng.integers(0, 16, (256, 4)), jnp.uint8),
                jnp.asarray(rng.normal(size=256), jnp.float32),
                jnp.asarray(rng.uniform(0.1, 1.0, 256), jnp.float32),
                jnp.asarray(rng.integers(0, 8, 256), jnp.int32),
                jnp.ones(256, bool))
    return [Case("level-0", fn, args()), Case("level-1", fn, args())]
