"""Sorted-levels lookup shared by every categorical indexer.

One implementation of the searchsorted/clip/verify pattern
(ValueIndexerModel, ClassBalancerModel, RecommendationIndexerModel,
AccessAnomalyModel all need it) so missing-value/dtype subtleties are fixed
in one place.
"""
from __future__ import annotations

import numpy as np


def lookup_levels(levels: np.ndarray, vals: np.ndarray):
    """(indices, found): position of each value in sorted `levels`; `found`
    False where the value is absent (caller decides the policy)."""
    levels = np.asarray(levels)
    vals = np.asarray(vals)
    idx = np.searchsorted(levels, vals)
    idx = np.clip(idx, 0, max(len(levels) - 1, 0))
    found = levels[idx] == vals if len(levels) else np.zeros(vals.shape, bool)
    return idx.astype(np.int64), found
