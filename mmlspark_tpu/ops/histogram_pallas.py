"""Pallas TPU kernel for GBDT histograms: scatter-add recast as MXU matmuls.

XLA lowers the (node, feature, bin) scatter-add to a serialized scatter —
~4s/tree at 1M x 32 on v5e. This kernel reformulates it:

    hist[n, f, b] = sum_rows stat[row] * [node(row)==n] * [bin(row,f)==b]
                  = (node_onehot * stat).T @ bin_onehot_f        per feature

i.e. a (T, 3m).T @ (T, B) matmul per (feature, row-tile) — systolic-array
work instead of scatter, with both one-hots materialized only in VMEM. All
three statistics (grad, hess, count) ride one matmul by stacking them into
the 3m columns.

Layout honors TPU tiling (sublane x lane = 8 x 128): bins arrive transposed
(F_pad, n) with F padded to a multiple of FEATURE_BLOCK; each grid cell
(fb, t) owns a (FEATURE_BLOCK features x TILE_ROWS rows) stripe and its
(FEATURE_BLOCK, m, B) output block, accumulated across row tiles (init at
t == 0). Row-aligned stats are (1, n) so the block (1, TILE_ROWS) matches
the full sublane dim.

Valid for m = 2^level nodes up to M_MAX (VMEM-bounded 3m matmul columns);
deeper levels fall back to the XLA scatter path (histogram.py routes).

PRECISION: grad/hess operands are rounded to bfloat16 before the MXU matmul
(~0.4% per-value; accumulation stays f32), so TPU training can pick different
splits than the XLA/CPU scatter path near gain ties. Where bit-reproducibility
across backends matters more than speed, set MMLSPARK_TPU_HIST=xla.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; run on both sides
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# tile sweep on v5e (1M-4M rows x 32 features x 64 bins): 8192/32 is ~5%
# faster than 4096/16; the VMEM worst case (m = M_MAX = 64 nodes with 256
# bins: 3x(32,64,256) f32 outputs + (256,8192) bf16 bin one-hot +
# (192,8192) bf16 stat rows) verified to compile and run on v5e
TILE_ROWS = 8192
FEATURE_BLOCK = 32
M_MAX = 64  # max nodes per level handled here (VMEM bound on the 3m columns)

# factored (radix) kernel routing: at high bin counts the direct kernel is
# VPU-bound on the (B, T) one-hot build (B x T compare+convert per feature
# — 26.9 ms/call at 1M x 128 x 256 on v5e, m-independent). Factoring
# b = hi * LO_BINS + lo replaces it with (B/LO_BINS + LO_BINS) x T of
# one-hot work plus 3m x (B/LO_BINS) x T of node-weight outer product;
# measured on v5e at 1M x 128 x 256: 13.4/15.4/22.6 ms for m=1/2/4 vs a flat
# 26.9 ms direct; at m >= 8 the outer product overtakes the saving (43.6
# ms). n_hi = 8 aligns the (3m, n_hi, T) outer product with the 8-sublane
# hardware tile (n_hi = 4 measured 30% SLOWER despite fewer ops).
# SUPERSEDED by the joint-key kernel below, which beats it at every m
# (12.0 vs 12.4 even at m=1) — FACTORED_M_MAX=0 retires the route; the
# kernel stays for the measurement history and as the joint kernel's
# structural ancestor.
FACTORED_MIN_BINS = 128
FACTORED_M_MAX = 0
LO_BINS = 32

# JOINT-key radix kernel (round-5): factor the COMBINED key
# k = node * B + bin as k = hi * LO + lo, so the node dimension rides the
# hi one-hot instead of a 3m-row outer product — the per-(feature, tile)
# VPU cost is ~(4mB/LO + LO) units against the direct kernel's (3m + B),
# minimized at LO ~= 2*sqrt(mB). Measured on v5e at 1M x 128 x 256
# (10-rep steady state):
#
#     m        1      2      4      8      16     (32+)
#     direct   26.8   26.8   26.8   26.8   26.8   26.8
#     old      12.4   14.5   21.7   43.6*  --         (separate-node, LO=32)
#     joint64  12.0   11.7   13.6   25.6   42.4
#     joint128 16.5   17.2   21.8   17.8   23.1
#
# (*round-4 measurement.) Routing below picks the measured winner per m:
# m <= 4 joint LO=64, m in {8, 16} joint LO=128, m >= 32 direct (joint's
# hi one-hot outgrows the saving). LO ~= 2*sqrt(mB) is the analytic
# optimum of the (4mB/LO + LO) VPU-unit model; the in-graph numbers
# (XLA CSEs the bins transpose, no per-call dispatch) run ~5 ms faster
# per call than this standalone table and follow the same ordering.
# Also measured and REJECTED:
# - row compaction (gather the ~50% live rows pre-kernel): at 1M rows
#   the compaction costs 9.7 ms (nonzero) + 14.9 ms (row gather of
#   (1M,128) u8) + ~9 ms per (1M,) f32 stat gather — TPU gathers run
#   ~10 GB/s, far under the 6-14 ms/level the halved kernel would save;
# - feature grouping (G features share one (G*rows, T)@(T, G*LO) MXU
#   pass, diagonal blocks kept): 5-15% SLOWER at every (m, G) tried —
#   the fixed per-level cost is not small-matmul streaming;
# - TILE_ROWS 16384/32768: flat (not per-grid-cell-overhead-bound).
JOINT_MIN_BINS = 128
JOINT_M_MAX = 16


def _joint_lo(m: int) -> int:
    return 64 if m <= 4 else 128


def _hist_kernel(bins_ref, node_ref, g_ref, h_ref, c_ref, hg_ref, hh_ref,
                 hc_ref, *, m: int, n_bins: int):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        hg_ref[...] = jnp.zeros_like(hg_ref)
        hh_ref[...] = jnp.zeros_like(hh_ref)
        hc_ref[...] = jnp.zeros_like(hc_ref)

    node = node_ref[0, :]   # (T,) i32 node id; outside [0, m) = inactive
    g = g_ref[0, :]
    h = h_ref[0, :]
    c = c_ref[0, :]         # bagging/padding count indicator (see histogram.py)
    T = node.shape[0]

    # Build BOTH matmul operands pre-transposed — (rows, T) with the
    # contraction dim in lanes — and contract dim 1 on each side. Mosaic
    # otherwise materializes VPU transposes of the K-major (T, small)
    # operands, which dominated the kernel 4x (measured 35ms -> 8ms at
    # 1M x 32 x 64 on v5e).
    #
    # bf16 one-hots: {0,1} and the stat values round once; the MXU
    # accumulates in f32 (preferred_element_type), so per-bin sums keep f32
    # accumulation error. Halves VPU one-hot traffic and doubles MXU rate
    # vs f32 operands.
    node_oh_t = (jax.lax.broadcasted_iota(jnp.int32, (m, T), 0)
                 == node[None, :]).astype(jnp.float32)       # (m, T)
    w_t = jnp.concatenate(
        [(node_oh_t * g[None, :]).astype(jnp.bfloat16),
         (node_oh_t * h[None, :]).astype(jnp.bfloat16),
         (node_oh_t * c[None, :]).astype(jnp.bfloat16)], axis=0)  # (3m, T)

    for i in range(FEATURE_BLOCK):  # static unroll over the feature stripe
        b = bins_ref[i, :].astype(jnp.int32)  # (T,) u8 -> i32 in VMEM
        bin_oh_t = (jax.lax.broadcasted_iota(jnp.int32, (n_bins, T), 0)
                    == b[None, :]).astype(jnp.bfloat16)      # (B, T)
        res = jax.lax.dot_general(w_t, bin_oh_t, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)  # (3m, B)
        hg_ref[i] += res[:m]
        hh_ref[i] += res[m:2 * m]
        hc_ref[i] += res[2 * m:]


def _hist_kernel_factored(bins_ref, node_ref, g_ref, h_ref, c_ref, hg_ref,
                          hh_ref, hc_ref, *, m: int, n_hi: int):
    """Radix variant of _hist_kernel for high bin counts: per feature,
    build hi (n_hi, T) and lo (LO_BINS, T) one-hots, lift the node-stat
    rows into per-hi planes U[(j, hi), t] = w[j, t] * hi_oh[hi, t] (the
    extra VPU cost), then ONE matmul U @ lo_oh.T yields the joint
    (3m * n_hi, LO_BINS) = (3, m, n_hi*LO_BINS) histogram block."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        hg_ref[...] = jnp.zeros_like(hg_ref)
        hh_ref[...] = jnp.zeros_like(hh_ref)
        hc_ref[...] = jnp.zeros_like(hc_ref)

    node = node_ref[0, :]
    g = g_ref[0, :]
    h = h_ref[0, :]
    c = c_ref[0, :]
    T = node.shape[0]

    node_oh_t = (jax.lax.broadcasted_iota(jnp.int32, (m, T), 0)
                 == node[None, :]).astype(jnp.float32)       # (m, T)
    w_t = jnp.concatenate(
        [(node_oh_t * g[None, :]).astype(jnp.bfloat16),
         (node_oh_t * h[None, :]).astype(jnp.bfloat16),
         (node_oh_t * c[None, :]).astype(jnp.bfloat16)], axis=0)  # (3m, T)

    for i in range(FEATURE_BLOCK):
        b = bins_ref[i, :].astype(jnp.int32)                 # (T,)
        hi = b // LO_BINS
        lo = b - hi * LO_BINS
        hi_oh = (jax.lax.broadcasted_iota(jnp.int32, (n_hi, T), 0)
                 == hi[None, :]).astype(jnp.bfloat16)        # (n_hi, T)
        lo_oh = (jax.lax.broadcasted_iota(jnp.int32, (LO_BINS, T), 0)
                 == lo[None, :]).astype(jnp.bfloat16)        # (LO, T)
        u = (w_t[:, None, :] * hi_oh[None, :, :]
             ).reshape(3 * m * n_hi, T)                      # (3m*hi, T)
        res = jax.lax.dot_general(u, lo_oh, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        # rows are (stat*m)-major, hi-minor; outputs stay (m, hi, LO) —
        # merging (hi, LO) into one lane dim is a Mosaic-unsupported
        # relayout, so the caller reshapes outside the kernel (free XLA)
        hg_ref[i] += res[:m * n_hi].reshape(m, n_hi, LO_BINS)
        hh_ref[i] += res[m * n_hi:2 * m * n_hi].reshape(m, n_hi, LO_BINS)
        hc_ref[i] += res[2 * m * n_hi:].reshape(m, n_hi, LO_BINS)


def _hist_kernel_joint(bins_ref, node_ref, g_ref, h_ref, c_ref, hg_ref,
                       hh_ref, hc_ref, *, m: int, n_hi: int, lo_bins: int,
                       n_bins: int):
    """Joint-key radix kernel: k = node * n_bins + bin factored over
    (hi, lo). The stats ride as THREE rows (no node dimension); the node
    enters through the hi one-hot, so the outer-product lift costs
    3 * n_hi * T instead of the separate-node variant's 3m * n_hi_b * T —
    that is what keeps deep levels (m = 8, 16) ahead of the direct
    kernel (measured table at the top of this file). Inactive rows carry
    key -1 -> hi -1, matching no hi one-hot row, so they vanish exactly
    like the direct kernel's node mask. (A count-plane shortcut — with
    unit counts the c lift IS hi_oh — was measured and REJECTED: the
    concatenate's layout copy costs more than the saved multiplies.)"""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        hg_ref[...] = jnp.zeros_like(hg_ref)
        hh_ref[...] = jnp.zeros_like(hh_ref)
        hc_ref[...] = jnp.zeros_like(hc_ref)

    node = node_ref[0, :]
    g = g_ref[0, :]
    h = h_ref[0, :]
    c = c_ref[0, :]
    T = node.shape[0]
    w3 = jnp.stack([g, h, c], axis=0).astype(jnp.bfloat16)   # (3, T)
    valid = (node >= 0) & (node < m)

    for i in range(FEATURE_BLOCK):
        b = bins_ref[i, :].astype(jnp.int32)                 # (T,)
        key = jnp.where(valid, node * n_bins + b, -1)        # [0, m*B)
        hi = key // lo_bins                                  # -1 drops out
        lo = key - hi * lo_bins
        hi_oh = (jax.lax.broadcasted_iota(jnp.int32, (n_hi, T), 0)
                 == hi[None, :]).astype(jnp.bfloat16)        # (n_hi, T)
        lo_oh = (jax.lax.broadcasted_iota(jnp.int32, (lo_bins, T), 0)
                 == lo[None, :]).astype(jnp.bfloat16)        # (LO, T)
        u = (w3[:, None, :] * hi_oh[None, :, :]).reshape(3 * n_hi, T)
        res = jax.lax.dot_general(u, lo_oh, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        hg_ref[i] += res[:n_hi].reshape(n_hi, lo_bins)
        hh_ref[i] += res[n_hi:2 * n_hi].reshape(n_hi, lo_bins)
        hc_ref[i] += res[2 * n_hi:].reshape(n_hi, lo_bins)


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "n_bins", "interpret"))
def pallas_hist(bins, grad, hess, node_local, active, n_nodes: int,
                n_bins: int, count_w=None, interpret: bool = False):
    """Same contract as histogram._xla_hist: (n,F) uint8 bins + per-row stats
    -> three (n_nodes, F, n_bins) f32 histograms."""
    n, F = bins.shape
    # uint8 end to end: the transpose stays 1 byte/element in HBM (an i32
    # operand would materialize 4x the traffic and a convert pass per level;
    # measured 1.67 -> 1.48 ms/call at 1M x 32 x 64 on v5e). XLA CSE dedupes
    # the transpose across the per-level calls in one tree.
    bins_t = bins.T  # (F, n) u8
    node = jnp.where(active, node_local, -1).astype(jnp.int32)
    cnt = (jnp.ones_like(hess) if count_w is None
           else count_w.astype(jnp.float32))

    pad_f = (-F) % FEATURE_BLOCK
    pad_n = (-n) % TILE_ROWS
    if pad_f or pad_n:
        bins_t = jnp.pad(bins_t, ((0, pad_f), (0, pad_n)))
        node = jnp.pad(node, (0, pad_n), constant_values=-1)
        grad = jnp.pad(grad, (0, pad_n))
        hess = jnp.pad(hess, (0, pad_n))
        cnt = jnp.pad(cnt, (0, pad_n))
    F_pad, n_pad = F + pad_f, n + pad_n
    nT = n_pad // TILE_ROWS
    nFB = F_pad // FEATURE_BLOCK

    node2 = node[None, :]
    g2 = grad.astype(jnp.float32)[None, :]
    h2 = hess.astype(jnp.float32)[None, :]
    c2 = cnt[None, :]

    factored = (n_bins >= FACTORED_MIN_BINS and n_nodes <= FACTORED_M_MAX)
    joint = (n_bins >= JOINT_MIN_BINS
             and FACTORED_M_MAX < n_nodes <= JOINT_M_MAX)
    row_spec = pl.BlockSpec((1, TILE_ROWS), lambda fb, t: (0, t))
    in_specs = [
        pl.BlockSpec((FEATURE_BLOCK, TILE_ROWS), lambda fb, t: (fb, t)),
        row_spec, row_spec, row_spec, row_spec,
    ]
    cparams = _CompilerParams(
        dimension_semantics=("parallel", "arbitrary"))
    if joint:
        # joint-key radix (see routing table above): pad the combined key
        # span m*B up to a LO multiple; padded key columns are never hit
        # (no row produces them) and are sliced off below
        lo = _joint_lo(n_nodes)
        key_span = n_nodes * n_bins
        key_pad = key_span + ((-key_span) % lo)
        n_hi = key_pad // lo
        kernel = functools.partial(_hist_kernel_joint, m=n_nodes,
                                   n_hi=n_hi, lo_bins=lo, n_bins=n_bins)
        hg, hh, hc = pl.pallas_call(
            kernel,
            grid=(nFB, nT),
            in_specs=in_specs,
            out_specs=[pl.BlockSpec((FEATURE_BLOCK, n_hi, lo),
                                    lambda fb, t: (fb, 0, 0))] * 3,
            out_shape=[jax.ShapeDtypeStruct((F_pad, n_hi, lo),
                                            jnp.float32)] * 3,
            compiler_params=cparams,
            interpret=interpret,
        )(bins_t, node2, g2, h2, c2)
        merge = lambda a: a.reshape(F_pad, key_pad)[:, :key_span].reshape(
            F_pad, n_nodes, n_bins)
        hg, hh, hc = merge(hg), merge(hh), merge(hc)
        return (hg[:F].transpose(1, 0, 2), hh[:F].transpose(1, 0, 2),
                hc[:F].transpose(1, 0, 2))
    if factored:
        # pad bins up to a LO_BINS multiple; padded bin columns stay zero
        # (no row carries them) and are sliced off below. Outputs are 4D
        # (F, m, hi, LO) inside the kernel; the (hi, LO) -> bins merge is
        # an XLA reshape out here
        n_bins_pad = n_bins + ((-n_bins) % LO_BINS)
        n_hi = n_bins_pad // LO_BINS
        kernel = functools.partial(_hist_kernel_factored, m=n_nodes,
                                   n_hi=n_hi)
        hg, hh, hc = pl.pallas_call(
            kernel,
            grid=(nFB, nT),
            in_specs=in_specs,
            out_specs=[pl.BlockSpec(
                (FEATURE_BLOCK, n_nodes, n_hi, LO_BINS),
                lambda fb, t: (fb, 0, 0, 0))] * 3,
            out_shape=[jax.ShapeDtypeStruct(
                (F_pad, n_nodes, n_hi, LO_BINS), jnp.float32)] * 3,
            compiler_params=cparams,
            interpret=interpret,
        )(bins_t, node2, g2, h2, c2)
        merge = lambda a: a.reshape(F_pad, n_nodes, n_bins_pad)
        hg, hh, hc = merge(hg), merge(hh), merge(hc)
    else:
        n_bins_pad = n_bins
        kernel = functools.partial(_hist_kernel, m=n_nodes, n_bins=n_bins)
        hg, hh, hc = pl.pallas_call(
            kernel,
            grid=(nFB, nT),
            in_specs=in_specs,
            out_specs=[pl.BlockSpec((FEATURE_BLOCK, n_nodes, n_bins),
                                    lambda fb, t: (fb, 0, 0))] * 3,
            out_shape=[jax.ShapeDtypeStruct((F_pad, n_nodes, n_bins),
                                            jnp.float32)] * 3,
            compiler_params=cparams,
            interpret=interpret,
        )(bins_t, node2, g2, h2, c2)
    # (F_pad, m, B_pad) -> (m, F, B)
    return (hg[:F, :, :n_bins].transpose(1, 0, 2),
            hh[:F, :, :n_bins].transpose(1, 0, 2),
            hc[:F, :, :n_bins].transpose(1, 0, 2))
