"""Pallas TPU kernel family for GBDT histograms: scatter-add as MXU matmuls.

XLA lowers the (node, feature, bin) scatter-add to a serialized scatter —
~4s/tree at 1M x 32 on v5e. This family reformulates it:

    hist[n, f, b] = sum_rows stat[row] * [node(row)==n] * [bin(row,f)==b]
                  = (node_onehot * stat).T @ bin_onehot_f        per feature

i.e. a (T, 3m).T @ (T, B) matmul per (feature, row-tile) — systolic-array
work instead of scatter, with both one-hots materialized only in VMEM. All
three statistics (grad, hess, count) ride one matmul by stacking them into
the 3m columns.

Layout honors TPU tiling (sublane x lane = 8 x 128): bins arrive transposed
(F_pad, n) with F padded to a multiple of FEATURE_BLOCK; each grid cell
(fb, t) owns a (FEATURE_BLOCK features x TILE_ROWS rows) stripe and its
(FEATURE_BLOCK, m, B) output block, accumulated across row tiles (init at
t == 0). Row-aligned stats are (1, n) so the block (1, TILE_ROWS) matches
the full sublane dim.

Valid for m = 2^level nodes up to M_MAX (VMEM-bounded 3m matmul columns);
deeper levels fall back to the XLA scatter path (histogram.py routes).

PRECISION CONTRACT: grad/hess operands are rounded to bfloat16 before the
MXU matmul (~0.4% per-value; accumulation stays f32), so TPU training can
pick different splits than the XLA/CPU scatter path near gain ties. The
precomputed one-hot planes (round 6) are exact {0,1} int8 and change
nothing about this contract. Where bit-reproducibility across backends
matters more than speed, set MMLSPARK_TPU_HIST=xla.

ROUTING (round 6). The family is one parametric kernel: factor the joint
key k = node * B + bin over radix digits (hi, lo) with k = hi * LO + lo.
LO = B degenerates to the DIRECT kernel (hi one-hot == node one-hot, lo
one-hot == bin one-hot); smaller LO trades the (B, T) bin-one-hot build
for a (mB/LO, T) hi build plus a 3 x (mB/LO) x T stat lift. The per-
(feature, tile) VPU-unit model is ~(2*LO + 5*mB/LO), minimized near
LO = sqrt(2.5*mB) — hardware-friendly LO comes from the table below.

    measured on v5e, 1M x 128 x 256, ms/call (rounds 4-5, 10-rep steady):
    m        1      2      4      8      16     (32+)
    direct   26.8   26.8   26.8   26.8   26.8   26.8
    joint64  12.0   11.7   13.6   25.6   42.4
    joint128 16.5   17.2   21.8   17.8   23.1

    routing table (kernel_route): per (m, B) -> LO, None = direct
    B >= 128 (measured):   m <= 4 -> 64;  m in (4, 16] -> 128; else direct
    64 <= B < 128 (analytic, round 6 — BENCH_MODE=hist measures the
    grid so the next round can pin measured values):
                           m <= 2 -> 16;  m in (2, 4]  -> 32;  else direct
    B < 64: direct (the bin one-hot is already small next to the lift).
    MMLSPARK_TPU_HIST_JOINT64=0 disables every narrow-lane (LO < 64)
    route — the B < 128 joint rows AND the LO=16/32 planes rows — falling
    back to direct: the escape hatch if Mosaic rejects those layouts on
    some TPU generation.

LEVEL-INVARIANT ONE-HOT REUSE (round 6). The lo digit of the joint key is
bin % LO whenever LO divides B — independent of the node assignment, i.e.
invariant across levels, trees, and boosting iterations. `build_hist_plan`
precomputes the lo one-hot planes ONCE per fit as (F_pad, LO, n_pad) int8
resident in HBM; `_hist_kernel_planes` streams them straight into the MXU
(one int8->bf16 convert per element instead of compare+select+convert),
leaving only the hi digit (mB/LO rows) built per level. Per-element VPU
model ~(LO + 5*mB/LO); HBM traffic grows to F*n*(1+LO) bytes per level —
this deliberately spends the ~50x memory headroom (hbm_utilization 0.018
at round 5) to buy VPU time. Planes require LO | B (plan_lo_bins), so the
wide 255-bin shape cannot take this route. Opt-in via
MMLSPARK_TPU_HIST=planes until the v5e A/B (emitted by bench.py into
BENCH_EXTRA_r06.json) proves a win: the analytic model puts planes within
~10-20% of the computed joint at the 8M x 32 x 64 headline because the
VPU saving is partially repaid as plane streaming (4 GB/level at LO=16).

Measured-and-REJECTED ledger (rounds 3-6):
- separate-node factored radix (round 4, b = hi*LO + lo with a 3m-row
  outer product): beaten by the joint-key form at every m (12.4 vs 12.0
  even at m=1); kernel deleted in round 6 — the joint kernel is its
  structural successor and the routing table no longer picks it.
- row compaction (gather the ~50% live rows pre-kernel): at 1M rows the
  compaction costs 9.7 ms (nonzero) + 14.9 ms (row gather of (1M,128)
  u8) + ~9 ms per (1M,) f32 stat gather — TPU gathers run ~10 GB/s, far
  under the 6-14 ms/level the halved kernel would save.
- feature grouping (G features share one (G*rows, T)@(T, G*LO) MXU pass,
  diagonal blocks kept): 5-15% SLOWER at every (m, G) tried.
- TILE_ROWS 16384/32768: flat (not per-grid-cell-overhead-bound).
- count-plane shortcut (unit counts make the c lift == hi_oh): the
  concatenate's layout copy costs more than the saved multiplies.
- FULL precomputed (B, n) one-hot planes (round 6, analytic): per-level
  streaming is F*n*(1+B) bytes = 16.6 GB at the headline — 20 ms/level
  at measured copy bandwidth, more than the whole VPU time it saves, and
  the resident planes (16 GB int8 at 8M x 32 x 64) do not fit v5e HBM
  next to the working set. The lo-plane form above is the viable subset.
- bit-packed planes (round 6, analytic): unpacking one bit per (lo, row)
  lane costs shift+mask+compare ~= the compare+select it replaces; the
  packing only reduces HBM traffic, which at 1.8% utilization is not the
  binding resource. Revisit only if planes win AND turn memory-bound.
- VMEM-cached one-hot reuse across a level's passes (round 6,
  structural): the 3-stat sharing already rides one matmul (the w3
  stack), sibling subtraction leaves exactly ONE kernel call per level,
  and VMEM does not persist across pallas_call invocations — there is no
  second pass left to share with inside a level.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; run on both sides
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# tile sweep on v5e (1M-4M rows x 32 features x 64 bins): 8192/32 is ~5%
# faster than 4096/16; the VMEM worst case (m = M_MAX = 64 nodes with 256
# bins: 3x(32,64,256) f32 outputs + (256,8192) bf16 bin one-hot +
# (192,8192) bf16 stat rows) verified to compile and run on v5e
TILE_ROWS = 8192
FEATURE_BLOCK = 32
M_MAX = 64  # max nodes per level handled here (VMEM bound on the 3m columns)

JOINT_MIN_BINS = 64   # round 6: the routed radix family now covers B = 64
JOINT_M_MAX = 16      # beyond this the hi one-hot outgrows the saving

# precomputed-plane route: (FEATURE_BLOCK, LO, T) int8 blocks are double-
# buffered by the pallas pipeline, so the plane route halves the row tile
# to keep 2 x FEATURE_BLOCK x LO x T int8 inside the VMEM budget
PLANES_TILE_ROWS = 4096
PLANES_M_MAX = 4      # deeper levels: the hi lift dominates, direct wins


def _env_joint64_enabled() -> bool:
    return os.environ.get("MMLSPARK_TPU_HIST_JOINT64", "1") != "0"


def plan_lo_bins(n_bins: int) -> int:
    """LO digit width for the precomputed-plane route (0 = unavailable).
    Planes need LO | B so that (node*B + bin) % LO == bin % LO is level-
    invariant — non-divisible bin counts (e.g. 255) cannot take the
    route — and LO < B (LO == B is the rejected full-plane form). B >= 128
    pairs with LO=64 (the measured joint64's digit); 64 <= B < 128 with
    LO=16 (the analytic optimum at the shallow m the route covers)."""
    if n_bins >= 128:
        return 64 if n_bins % 64 == 0 else 0
    if n_bins >= JOINT_MIN_BINS and n_bins % 16 == 0:
        return 16
    return 0


def kernel_route(n_nodes: int, n_bins: int, has_planes: bool = False):
    """Kernel selection per (m, B): ('direct'|'joint'|'planes', LO).

    The table at the top of this file is THE source of truth; this
    function is its executable form (pinned by tests so a silent route
    change is a visible diff). `has_planes` marks a fit that prebuilt
    level-invariant lo one-hot planes (build_hist_plan)."""
    if has_planes and n_nodes <= PLANES_M_MAX:
        lo = plan_lo_bins(n_bins)
        # the narrow-lane escape hatch covers planes too: LO=16/32 plane
        # blocks use the same unproven lane widths as the B<128 joint rows
        if lo and (lo >= 64 or _env_joint64_enabled()):
            return ("planes", lo)
    if n_bins >= 128 and n_nodes <= JOINT_M_MAX:
        return ("joint", 64 if n_nodes <= 4 else 128)
    if 128 > n_bins >= JOINT_MIN_BINS and n_nodes <= 4 \
            and _env_joint64_enabled():
        return ("joint", 16 if n_nodes <= 2 else 32)
    return ("direct", n_bins)


def _hist_kernel(bins_ref, node_ref, g_ref, h_ref, c_ref, hg_ref, hh_ref,
                 hc_ref, *, m: int, n_bins: int):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        hg_ref[...] = jnp.zeros_like(hg_ref)
        hh_ref[...] = jnp.zeros_like(hh_ref)
        hc_ref[...] = jnp.zeros_like(hc_ref)

    node = node_ref[0, :]   # (T,) i32 node id; outside [0, m) = inactive
    g = g_ref[0, :]
    h = h_ref[0, :]
    c = c_ref[0, :]         # bagging/padding count indicator (see histogram.py)
    T = node.shape[0]

    # Build BOTH matmul operands pre-transposed — (rows, T) with the
    # contraction dim in lanes — and contract dim 1 on each side. Mosaic
    # otherwise materializes VPU transposes of the K-major (T, small)
    # operands, which dominated the kernel 4x (measured 35ms -> 8ms at
    # 1M x 32 x 64 on v5e).
    #
    # bf16 one-hots: {0,1} and the stat values round once; the MXU
    # accumulates in f32 (preferred_element_type), so per-bin sums keep f32
    # accumulation error. Halves VPU one-hot traffic and doubles MXU rate
    # vs f32 operands.
    node_oh_t = (jax.lax.broadcasted_iota(jnp.int32, (m, T), 0)
                 == node[None, :]).astype(jnp.float32)       # (m, T)
    w_t = jnp.concatenate(
        [(node_oh_t * g[None, :]).astype(jnp.bfloat16),
         (node_oh_t * h[None, :]).astype(jnp.bfloat16),
         (node_oh_t * c[None, :]).astype(jnp.bfloat16)], axis=0)  # (3m, T)

    for i in range(FEATURE_BLOCK):  # static unroll over the feature stripe
        b = bins_ref[i, :].astype(jnp.int32)  # (T,) u8 -> i32 in VMEM
        bin_oh_t = (jax.lax.broadcasted_iota(jnp.int32, (n_bins, T), 0)
                    == b[None, :]).astype(jnp.bfloat16)      # (B, T)
        res = jax.lax.dot_general(w_t, bin_oh_t, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)  # (3m, B)
        hg_ref[i] += res[:m]
        hh_ref[i] += res[m:2 * m]
        hc_ref[i] += res[2 * m:]


def _hist_kernel_joint(bins_ref, node_ref, g_ref, h_ref, c_ref, hg_ref,
                       hh_ref, hc_ref, *, m: int, n_hi: int, lo_bins: int,
                       n_bins: int):
    """Joint-key radix kernel: k = node * n_bins + bin factored over
    (hi, lo). The stats ride as THREE rows (no node dimension); the node
    enters through the hi one-hot, so the outer-product lift costs
    3 * n_hi * T — that is what keeps the routed (m, B) points ahead of
    the direct kernel (measured/analytic table at the top of this file).
    Inactive rows carry key -1 -> hi -1, matching no hi one-hot row, so
    they vanish exactly like the direct kernel's node mask."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        hg_ref[...] = jnp.zeros_like(hg_ref)
        hh_ref[...] = jnp.zeros_like(hh_ref)
        hc_ref[...] = jnp.zeros_like(hc_ref)

    node = node_ref[0, :]
    g = g_ref[0, :]
    h = h_ref[0, :]
    c = c_ref[0, :]
    T = node.shape[0]
    w3 = jnp.stack([g, h, c], axis=0).astype(jnp.bfloat16)   # (3, T)
    valid = (node >= 0) & (node < m)

    for i in range(FEATURE_BLOCK):
        b = bins_ref[i, :].astype(jnp.int32)                 # (T,)
        key = jnp.where(valid, node * n_bins + b, -1)        # [0, m*B)
        hi = key // lo_bins                                  # -1 drops out
        lo = key - hi * lo_bins
        hi_oh = (jax.lax.broadcasted_iota(jnp.int32, (n_hi, T), 0)
                 == hi[None, :]).astype(jnp.bfloat16)        # (n_hi, T)
        lo_oh = (jax.lax.broadcasted_iota(jnp.int32, (lo_bins, T), 0)
                 == lo[None, :]).astype(jnp.bfloat16)        # (LO, T)
        u = (w3[:, None, :] * hi_oh[None, :, :]).reshape(3 * n_hi, T)
        res = jax.lax.dot_general(u, lo_oh, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        hg_ref[i] += res[:n_hi].reshape(n_hi, lo_bins)
        hh_ref[i] += res[n_hi:2 * n_hi].reshape(n_hi, lo_bins)
        hc_ref[i] += res[2 * n_hi:].reshape(n_hi, lo_bins)


def _hist_kernel_planes(planes_ref, bins_ref, node_ref, g_ref, h_ref, c_ref,
                        hg_ref, hh_ref, hc_ref, *, m: int, n_hi: int,
                        lo_bins: int, n_bins: int):
    """Joint-key radix with the level-invariant lo one-hot PRECOMPUTED
    (build_hist_plan): planes_ref holds (FEATURE_BLOCK, LO, T) int8 lo
    one-hots of bin % LO, streamed from HBM straight into the matmul (one
    convert per element — no compare/select rebuild per level). Only the
    hi digit hi = node*(B/LO) + bin//LO is built here; LO | B guarantees
    the key span m*B splits exactly into n_hi = m*B/LO rows (no key
    padding). Inactive rows get hi < 0 and vanish via the hi one-hot."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        hg_ref[...] = jnp.zeros_like(hg_ref)
        hh_ref[...] = jnp.zeros_like(hh_ref)
        hc_ref[...] = jnp.zeros_like(hc_ref)

    node = node_ref[0, :]
    g = g_ref[0, :]
    h = h_ref[0, :]
    c = c_ref[0, :]
    T = node.shape[0]
    w3 = jnp.stack([g, h, c], axis=0).astype(jnp.bfloat16)   # (3, T)
    nb_hi = n_bins // lo_bins
    valid = (node >= 0) & (node < m)
    # invalid rows: base -nb_hi keeps hi negative after adding bin//LO
    node_hi = jnp.where(valid, node * nb_hi, -nb_hi)         # (T,)

    for i in range(FEATURE_BLOCK):
        b = bins_ref[i, :].astype(jnp.int32)                 # (T,)
        hi = node_hi + b // lo_bins                          # < 0 drops out
        hi_oh = (jax.lax.broadcasted_iota(jnp.int32, (n_hi, T), 0)
                 == hi[None, :]).astype(jnp.bfloat16)        # (n_hi, T)
        lo_oh = planes_ref[i].astype(jnp.bfloat16)           # (LO, T)
        u = (w3[:, None, :] * hi_oh[None, :, :]).reshape(3 * n_hi, T)
        res = jax.lax.dot_general(u, lo_oh, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        hg_ref[i] += res[:n_hi].reshape(n_hi, lo_bins)
        hh_ref[i] += res[n_hi:2 * n_hi].reshape(n_hi, lo_bins)
        hc_ref[i] += res[2 * n_hi:].reshape(n_hi, lo_bins)


@functools.partial(jax.jit, static_argnames=("n_bins",))
def build_hist_plan(bins, n_bins: int):
    """Level-invariant histogram plan: (F_pad, LO, n_pad) int8 one-hot of
    bin % LO, built ONCE per fit (the bins never change across levels,
    trees, or boosting iterations) and resident in HBM — F*LO*n bytes
    (4 GB at 8M x 32 with LO=16). Padding matches the planes kernel's
    grid (FEATURE_BLOCK x PLANES_TILE_ROWS); padded rows one-hot lo=0
    but are dropped by the kernel's node mask. Returns None-equivalent
    (raises) when plan_lo_bins(n_bins) == 0 — callers gate on it."""
    lo = plan_lo_bins(n_bins)
    if not lo:
        raise ValueError(f"no plane digit divides n_bins={n_bins}; "
                         "the planes route needs LO | B (plan_lo_bins)")
    n, F = bins.shape
    pad_f = (-F) % FEATURE_BLOCK
    pad_n = (-n) % PLANES_TILE_ROWS
    bt = bins.T  # (F, n) u8
    if pad_f or pad_n:
        bt = jnp.pad(bt, ((0, pad_f), (0, pad_n)))
    lo_val = bt.astype(jnp.int32) % lo                       # (F_pad, n_pad)
    return (jax.lax.broadcasted_iota(
        jnp.int32, (bt.shape[0], lo, bt.shape[1]), 1)
        == lo_val[:, None, :]).astype(jnp.int8)


@functools.partial(jax.jit,
                   static_argnames=("n_nodes", "n_bins", "plane_lo",
                                    "route", "interpret"))
def pallas_hist(bins, grad, hess, node_local, active, n_nodes: int,
                n_bins: int, count_w=None, lo_planes=None, plane_lo: int = 0,
                route=None, interpret: bool = False):
    """Same contract as histogram._xla_hist: (n,F) uint8 bins + per-row stats
    -> three (n_nodes, F, n_bins) f32 histograms.

    `lo_planes`/`plane_lo`: per-fit precomputed lo one-hot planes from
    build_hist_plan — enables the 'planes' route for shallow levels.
    `route`: explicit ('direct'|'joint'|'planes', LO) override, the
    bench/test hook behind BENCH_MODE=hist's per-route grid; None = the
    kernel_route table."""
    n, F = bins.shape
    # uint8 end to end: the transpose stays 1 byte/element in HBM (an i32
    # operand would materialize 4x the traffic and a convert pass per level;
    # measured 1.67 -> 1.48 ms/call at 1M x 32 x 64 on v5e). XLA CSE dedupes
    # the transpose across the per-level calls in one tree.
    bins_t = bins.T  # (F, n) u8
    node = jnp.where(active, node_local, -1).astype(jnp.int32)
    cnt = (jnp.ones_like(hess) if count_w is None
           else count_w.astype(jnp.float32))

    if route is None:
        route = kernel_route(n_nodes, n_bins,
                             has_planes=(lo_planes is not None
                                         and plane_lo > 0))
    kind, lo = route
    if kind == "planes" and (lo_planes is None or plane_lo != lo):
        raise ValueError(f"planes route at LO={lo} needs matching "
                         f"build_hist_plan output (got plane_lo={plane_lo})")

    tile_rows = PLANES_TILE_ROWS if kind == "planes" else TILE_ROWS
    pad_f = (-F) % FEATURE_BLOCK
    pad_n = (-n) % tile_rows
    if pad_f or pad_n:
        bins_t = jnp.pad(bins_t, ((0, pad_f), (0, pad_n)))
        node = jnp.pad(node, (0, pad_n), constant_values=-1)
        grad = jnp.pad(grad, (0, pad_n))
        hess = jnp.pad(hess, (0, pad_n))
        cnt = jnp.pad(cnt, (0, pad_n))
    F_pad, n_pad = F + pad_f, n + pad_n
    nT = n_pad // tile_rows
    nFB = F_pad // FEATURE_BLOCK

    node2 = node[None, :]
    g2 = grad.astype(jnp.float32)[None, :]
    h2 = hess.astype(jnp.float32)[None, :]
    c2 = cnt[None, :]

    row_spec = pl.BlockSpec((1, tile_rows), lambda fb, t: (0, t))
    in_specs = [
        pl.BlockSpec((FEATURE_BLOCK, tile_rows), lambda fb, t: (fb, t)),
        row_spec, row_spec, row_spec, row_spec,
    ]
    cparams = _CompilerParams(
        dimension_semantics=("parallel", "arbitrary"))

    if kind == "planes":
        if lo_planes.shape != (F_pad, lo, n_pad):
            raise ValueError(
                f"hist plan shape {lo_planes.shape} does not match this "
                f"call's padded ({F_pad}, {lo}, {n_pad}) — the plan must "
                f"be built from the SAME bins matrix (build_hist_plan)")
        n_hi = n_nodes * (n_bins // lo)          # LO | B: exact key span
        kernel = functools.partial(_hist_kernel_planes, m=n_nodes,
                                   n_hi=n_hi, lo_bins=lo, n_bins=n_bins)
        plane_spec = pl.BlockSpec((FEATURE_BLOCK, lo, tile_rows),
                                  lambda fb, t: (fb, 0, t))
        hg, hh, hc = pl.pallas_call(
            kernel,
            grid=(nFB, nT),
            in_specs=[plane_spec] + in_specs,
            out_specs=[pl.BlockSpec((FEATURE_BLOCK, n_hi, lo),
                                    lambda fb, t: (fb, 0, 0))] * 3,
            out_shape=[jax.ShapeDtypeStruct((F_pad, n_hi, lo),
                                            jnp.float32)] * 3,
            compiler_params=cparams,
            interpret=interpret,
        )(lo_planes, bins_t, node2, g2, h2, c2)
        merge = lambda a: a.reshape(F_pad, n_nodes, n_bins)
        hg, hh, hc = merge(hg), merge(hh), merge(hc)
        return (hg[:F].transpose(1, 0, 2), hh[:F].transpose(1, 0, 2),
                hc[:F].transpose(1, 0, 2))
    if kind == "joint":
        # joint-key radix (see routing table above): pad the combined key
        # span m*B up to a LO multiple; padded key columns are never hit
        # (no row produces them) and are sliced off below
        key_span = n_nodes * n_bins
        key_pad = key_span + ((-key_span) % lo)
        n_hi = key_pad // lo
        kernel = functools.partial(_hist_kernel_joint, m=n_nodes,
                                   n_hi=n_hi, lo_bins=lo, n_bins=n_bins)
        hg, hh, hc = pl.pallas_call(
            kernel,
            grid=(nFB, nT),
            in_specs=in_specs,
            out_specs=[pl.BlockSpec((FEATURE_BLOCK, n_hi, lo),
                                    lambda fb, t: (fb, 0, 0))] * 3,
            out_shape=[jax.ShapeDtypeStruct((F_pad, n_hi, lo),
                                            jnp.float32)] * 3,
            compiler_params=cparams,
            interpret=interpret,
        )(bins_t, node2, g2, h2, c2)
        merge = lambda a: a.reshape(F_pad, key_pad)[:, :key_span].reshape(
            F_pad, n_nodes, n_bins)
        hg, hh, hc = merge(hg), merge(hh), merge(hc)
        return (hg[:F].transpose(1, 0, 2), hh[:F].transpose(1, 0, 2),
                hc[:F].transpose(1, 0, 2))
    kernel = functools.partial(_hist_kernel, m=n_nodes, n_bins=n_bins)
    hg, hh, hc = pl.pallas_call(
        kernel,
        grid=(nFB, nT),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((FEATURE_BLOCK, n_nodes, n_bins),
                                lambda fb, t: (fb, 0, 0))] * 3,
        out_shape=[jax.ShapeDtypeStruct((F_pad, n_nodes, n_bins),
                                        jnp.float32)] * 3,
        compiler_params=cparams,
        interpret=interpret,
    )(bins_t, node2, g2, h2, c2)
    # (F_pad, m, B) -> (m, F, B)
    return (hg[:F, :, :n_bins].transpose(1, 0, 2),
            hh[:F, :, :n_bins].transpose(1, 0, 2),
            hc[:F, :, :n_bins].transpose(1, 0, 2))
