"""MurmurHash3 (x86 32-bit) — the hashing primitive for hashed featurization.

Role-equivalent to the reference's VowpalWabbitMurmurWithPrefix
(vw/featurizer/VowpalWabbitMurmurWithPrefix.scala) and Spark's hashTF murmur.
Pure Python over bytes with a memoizing vectorizer for string columns (host
side — hashing happens before device transfer, like the reference hashes in
the JVM before JNI).
"""
from __future__ import annotations

import functools

import numpy as np


def _rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF


def murmur3_32(data: bytes, seed: int = 0) -> int:
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = _rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[4 * nblocks:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = _rotl32(k, 15)
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


@functools.lru_cache(maxsize=1_000_000)
def hash_token(token: str, seed: int = 0) -> int:
    return murmur3_32(token.encode("utf-8"), seed)


def hash_strings(values, seed: int = 0, num_bits: int = 18) -> np.ndarray:
    """Vectorized hash of a string column into [0, 2^num_bits). Large batches
    route to the native C++ kernel (native/kernels.cpp murmur3_batch — same
    bit-exact algorithm) when the toolchain built it; otherwise the memoized
    Python path runs."""
    if len(values) >= 1024:
        from ..native import hash_strings_native
        out = hash_strings_native(values, seed=seed, num_bits=num_bits)
        if out is not None:
            return out
    mask = (1 << num_bits) - 1
    return np.fromiter((hash_token(str(v), seed) & mask for v in values),
                       dtype=np.int64, count=len(values))
