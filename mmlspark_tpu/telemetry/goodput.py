"""Training-loop goodput/MFU accounting and straggler detection.

The serving tier answers "is the fleet healthy" with windows, SLOs and
tail traces (PRs 5/7/8); the training side could only say *a step
happened* (`train.step` spans). This module closes the gap with two
pieces (docs/observability.md "Training observability"):

- **StepClock**: driven by `TrainingSupervisor` (and `fit_booster`'s
  host loop / `ShardedLMTrainer.run_stream`), it decomposes every step's
  wall time into phases —

    * `data_wait`   — consumer blocked on an empty `DevicePrefetcher`
                      queue (the overlap failed to hide the producer),
    * `device`      — time inside an explicit block-until-ready boundary
                      (`device_block`); async dispatch surfaces device
                      time wherever the loop actually syncs,
    * `checkpoint`  — snapshot + submit stall on the step thread,
    * `lost`        — restart/replay rewinds, failed step attempts, and
                      injected stalls (time that produced no state),
    * `host`        — the remainder of the step wall —

  rolled into **goodput** = 1 - (data_wait + checkpoint + lost) / wall
  and, when a per-step flops figure is known (from the `CompileLog`
  cost analysis PR 8 records per executable, or supplied analytically),
  a **model-flops-utilization** gauge. Per-step walls and phases land
  in windowed histograms (`train.step.wall`, `train.step.{phase}`) so
  the verdict reflects the last N seconds, and the accounting state
  rides the supervisor's checkpoint payload so a killed-and-resumed run
  keeps its cumulative goodput. These per-step/per-executable rows are
  exactly what *A Learned Performance Model for TPUs* (PAPERS.md)
  trains on.

- **StragglerDetector**: multi-process runs exchange per-host windowed
  step p50s through the existing `parallel/cluster.Heartbeat` files
  (`beat(epoch, stats=...)`); each host reads every peer's file on its
  own beat, computes the fleet median, and flags hosts whose p50
  deviates beyond `threshold` x median — a `train.straggler` event on
  the flag TRANSITION plus the `train.stragglers` gauge. Deterministic
  under a seeded `FaultInjector` delay fault (the delay lands in `lost`,
  inflates that host's p50, and sinks its goodput below the SLO floor —
  the burn that makes the flight recorder dump a bundle carrying this
  module's snapshot). *CTA-Pipelining* (PAPERS.md) motivates the
  bubble/straggler attribution as the scaling signal.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Optional

from ..reliability.metrics import reliability_metrics
from . import names as tnames
from .spans import get_tracer

PHASES = ("data_wait", "host", "device", "checkpoint", "lost")

# Optional peak-flops anchor for the MFU gauge (TFLOP/s of the target
# chip, e.g. 197 for v5e bf16). Unset -> MFU degrades to absent, never a
# guessed denominator.
PEAK_TFLOPS_ENV = "MMLSPARK_TPU_PEAK_TFLOPS"


def peak_flops_from_env() -> Optional[float]:
    """Peak FLOP/s from ``MMLSPARK_TPU_PEAK_TFLOPS`` (TFLOP/s), or None —
    the documented MFU degrade on hosts that never declared a peak."""
    raw = os.environ.get(PEAK_TFLOPS_ENV)
    if not raw:
        return None
    try:
        tflops = float(raw)
    except ValueError:
        return None
    return tflops * 1e12 if tflops > 0 else None


def flops_from_compile_log(fingerprint_prefix: str, log=None
                           ) -> Optional[float]:
    """Per-step flops from the newest compile record whose fingerprint
    starts with `fingerprint_prefix` and carries a cost analysis — how a
    trainer that compiled through `telemetry.perf` feeds its own MFU.
    None when no matching record reported flops (CPU backends report
    cost; a backend that omits it degrades MFU to absent)."""
    from .perf import get_compile_log
    records = (log if log is not None else get_compile_log()).records()
    for rec in reversed(records):
        if not str(rec.get("fingerprint", "")).startswith(fingerprint_prefix):
            continue
        analysis = rec.get("analysis") or {}
        flops = analysis.get("flops")
        if isinstance(flops, (int, float)) and flops > 0:
            return float(flops)
    return None


class StepClock:
    """Phase-decomposed training-step accounting (see module docstring).

    Thread contract: one step is active at a time (the training loop's);
    `note()` may arrive from other threads (the prefetch consumer side
    runs inside the step, the feeder never notes) and is attributed to
    the active step when one is open, to the run otherwise. All state
    sits behind one lock with tiny critical sections — no I/O, no
    blocking call is ever made under it.
    """

    # state_vector layout (rides the supervisor checkpoint payload as a
    # float64 array; append-only so older checkpoints keep restoring)
    _STATE_FIELDS = ("wall_s", "lost_s", "data_wait_s", "checkpoint_s",
                     "device_s", "steps", "since_mark_s")

    def __init__(self, registry=None, tracer=None,
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 recent_steps: int = 64, install: bool = True):
        self._metrics = registry if registry is not None \
            else reliability_metrics
        self._tracer = tracer
        self.flops_per_step = flops_per_step
        self.peak_flops = (peak_flops if peak_flops is not None
                           else peak_flops_from_env())
        self._lock = threading.Lock()
        self._wall_s = 0.0          # every accounted second lands here
        self._lost_s = 0.0
        self._data_wait_s = 0.0
        self._checkpoint_s = 0.0
        self._device_s = 0.0
        self._steps = 0             # completed step attempts
        self._since_mark_s = 0.0    # productive wall since the last mark
        self._in_step = False
        self._step_notes: dict = {}
        self._recent: deque = deque(maxlen=max(int(recent_steps), 4))
        if install:
            install_clock(self)

    # -- collaborator notes ---------------------------------------------------
    def note(self, phase: str, seconds: float) -> None:
        """Attribute `seconds` to a phase. Inside a step the time is part
        of the step's wall (the step context manager measured it already);
        outside (e.g. the supervisor's checkpoint mark between steps) it
        extends the run wall too."""
        if phase not in PHASES:
            raise ValueError(f"unknown phase {phase!r}; one of {PHASES}")
        s = max(float(seconds), 0.0)
        with self._lock:
            if self._in_step:
                self._step_notes[phase] = self._step_notes.get(phase, 0.0) + s
                return
            self._wall_s += s
            self._add_phase(phase, s)
        # out-of-step notes move the goodput denominator: keep the
        # gauges current (in-step notes fold in at the step boundary)
        self._publish(step_wall_s=None)

    def _add_phase(self, phase: str, s: float) -> None:
        # lock held by caller
        if phase == "data_wait":
            self._data_wait_s += s
        elif phase == "checkpoint":
            self._checkpoint_s += s
        elif phase == "device":
            self._device_s += s
        elif phase == "lost":
            self._lost_s += s
        # "host" is the derived remainder; an explicit host note is wall-only

    # -- the step boundary ----------------------------------------------------
    @contextmanager
    def step(self, step: Optional[int] = None):
        """Measure one step attempt. A clean exit books the wall as
        productive (minus in-step notes, which keep their phases); an
        exception books the WHOLE attempt as lost — the restart machinery
        is about to throw this work away."""
        with self._lock:
            self._in_step = True
            self._step_notes = {}
        t0 = time.perf_counter()
        try:
            yield self
        except BaseException:
            dt = time.perf_counter() - t0
            with self._lock:
                self._in_step = False
                self._wall_s += dt
                self._lost_s += dt
                # NOT a completed step: it stays out of _steps (the MFU
                # numerator and the straggler p50 count real work only)
            self._publish(step_wall_s=None)
            raise
        dt = time.perf_counter() - t0
        with self._lock:
            self._in_step = False
            notes = self._step_notes
            self._step_notes = {}
            self._wall_s += dt
            self._steps += 1
            noted = 0.0
            for phase, s in notes.items():
                s = min(s, dt - noted)       # notes can't exceed the wall
                self._add_phase(phase, s)
                noted += s
            self._since_mark_s += self._rewindable(dt, notes)
            self._recent.append(dt * 1000.0)
        self._publish(step_wall_s=dt, notes=notes)

    @staticmethod
    def _rewindable(wall_s: float, notes: dict) -> float:
        """The part of a step's wall a later rewind may move to lost:
        everything already attributed to a non-productive phase stays in
        that phase's account (moving it again would double-count it in
        the goodput denominator)."""
        bad = sum(notes.get(p, 0.0)
                  for p in ("lost", "data_wait", "checkpoint"))
        return max(wall_s - bad, 0.0)

    def add_step(self, wall_s: float, notes: Optional[dict] = None) -> None:
        """Record one COMPLETED step measured externally — for host loops
        that time their own iterations and cannot wrap the `step()`
        context manager around a body with break/continue paths. `notes`
        attributes parts of that wall to phases (same keys as `note`)."""
        wall_s = max(float(wall_s), 0.0)
        notes = dict(notes or {})
        with self._lock:
            self._wall_s += wall_s
            self._steps += 1
            noted = 0.0
            for phase, s in notes.items():
                s = min(max(float(s), 0.0), wall_s - noted)
                self._add_phase(phase, s)
                noted += s
            self._since_mark_s += self._rewindable(wall_s, notes)
            self._recent.append(wall_s * 1000.0)
        self._publish(step_wall_s=wall_s, notes=notes)

    def device_block(self, fn: Callable):
        """Run `fn` (a block-until-ready boundary: `float(loss)`, a packed
        fetch) and book its time as device-compute."""
        t0 = time.perf_counter()
        try:
            return fn()
        finally:
            self.note("device", time.perf_counter() - t0)

    # -- rewind/mark bookkeeping (supervisor hooks) ---------------------------
    def marked(self) -> None:
        """A durable snapshot was taken: work before this point can no
        longer be lost to an in-process rewind."""
        with self._lock:
            self._since_mark_s = 0.0

    def rewound(self) -> None:
        """The loop restarted from the last snapshot: everything since
        that mark will be re-executed, so its wall moves to lost."""
        with self._lock:
            self._lost_s += self._since_mark_s
            self._since_mark_s = 0.0
        self._publish(step_wall_s=None)

    # -- checkpoint ride-along ------------------------------------------------
    def state_vector(self) -> list:
        """Accounting state as a flat float list (the supervisor stores it
        as a float64 array in the checkpoint payload)."""
        with self._lock:
            # since_mark exports as 0: a restored run stands exactly AT
            # its mark, with nothing rewindable behind it
            return [self._wall_s, self._lost_s, self._data_wait_s,
                    self._checkpoint_s, self._device_s, float(self._steps),
                    0.0]

    def restore_state(self, vec) -> None:
        """Adopt a prior run's accounting (resume path): cumulative
        goodput then spans the preemption instead of resetting to 1.0."""
        vals = [float(v) for v in vec]
        vals += [0.0] * (len(self._STATE_FIELDS) - len(vals))
        with self._lock:
            (self._wall_s, self._lost_s, self._data_wait_s,
             self._checkpoint_s, self._device_s, steps,
             self._since_mark_s) = vals[:7]
            self._steps = int(steps)
        self._publish(step_wall_s=None)

    def publish(self) -> None:
        """Refresh the goodput/MFU/lost gauges now (the supervisor calls
        this at finalize so the last checkpoint note is visible)."""
        self._publish(step_wall_s=None)

    # -- read side ------------------------------------------------------------
    def goodput(self) -> float:
        with self._lock:
            return self._goodput_locked()

    def _goodput_locked(self) -> float:
        if self._wall_s <= 0.0:
            return 1.0
        bad = self._lost_s + self._data_wait_s + self._checkpoint_s
        return max(1.0 - bad / self._wall_s, 0.0)

    def mfu(self) -> Optional[float]:
        """flops_per_step * steps / (wall * peak_flops); None (the
        documented degrade) when either flops side is unknown."""
        with self._lock:
            wall, steps = self._wall_s, self._steps
        if (self.flops_per_step is None or self.peak_flops is None
                or wall <= 0.0 or self.peak_flops <= 0.0):
            return None
        return self.flops_per_step * steps / (wall * self.peak_flops)

    def step_p50_ms(self) -> float:
        """Windowed (recent-steps) step-wall median — what the heartbeat
        exchanges for straggler detection."""
        with self._lock:
            recent = sorted(self._recent)
        return recent[len(recent) // 2] if recent else 0.0

    def beat_stats(self) -> dict:
        """The per-host stats a Heartbeat.beat carries to peers."""
        with self._lock:
            steps = self._steps
            goodput = self._goodput_locked()
        return {"step_p50_ms": round(self.step_p50_ms(), 3),
                "steps": steps, "goodput": round(goodput, 4)}

    def snapshot(self) -> dict:
        """The step-phase breakdown (what a flight-recorder bundle's
        goodput.json holds and bench prints)."""
        with self._lock:
            wall = self._wall_s
            phases = {"data_wait_s": self._data_wait_s,
                      "device_s": self._device_s,
                      "checkpoint_s": self._checkpoint_s,
                      "lost_s": self._lost_s}
            phases["host_s"] = max(wall - sum(phases.values()), 0.0)
            steps = self._steps
            goodput = self._goodput_locked()
        mfu = self.mfu()
        return {"steps": steps, "wall_s": wall, "goodput": goodput,
                "mfu": mfu, "step_p50_ms": self.step_p50_ms(),
                "phases": phases}

    # -- metric publication ---------------------------------------------------
    def _publish(self, step_wall_s: Optional[float],
                 notes: Optional[dict] = None) -> None:
        """Gauges on every accounting change; histograms per completed
        step. Never under the clock lock (the registry has its own)."""
        m = self._metrics
        m.set_gauge(tnames.TRAIN_GOODPUT, round(self.goodput(), 6))
        with self._lock:
            lost = self._lost_s
        m.set_gauge(tnames.TRAIN_LOST_SECONDS, round(lost, 6))
        mfu = self.mfu()
        if mfu is not None:
            m.set_gauge(tnames.TRAIN_MFU, round(mfu, 6))
        if step_wall_s is None:
            return
        m.observe_ms(tnames.TRAIN_STEP_WALL, step_wall_s * 1000.0)
        noted = 0.0
        for phase, s in (notes or {}).items():
            noted += s
            if s > 0.0:
                m.observe_ms(tnames.train_step_phase(phase), s * 1000.0)
        # the derived remainder is a phase too — without it the
        # documented train.step.host series would never exist
        host_s = max(step_wall_s - noted, 0.0)
        if host_s > 0.0:
            m.observe_ms(tnames.train_step_phase("host"), host_s * 1000.0)


class StragglerDetector:
    """Flag hosts whose windowed step p50 deviates beyond `threshold` x
    the fleet median, from heartbeat-exchanged stats (module docstring).
    Driven by the supervisor on each of its own beats; every host runs
    the same check over the same files, so every host agrees."""

    def __init__(self, heartbeat, threshold: float = 1.5,
                 min_steps: int = 4, registry=None, tracer=None,
                 profile_on_flag: bool = True,
                 max_age_s: Optional[float] = 30.0):
        self.heartbeat = heartbeat
        self.threshold = float(threshold)
        self.min_steps = max(int(min_steps), 1)
        # a crashed host's LAST row is frozen-but-plausible: without an
        # age cut the detector would evaluate it forever and never flag
        # anything (liveness is HostLeases' job — here stale rows just
        # leave the straggler math). None disables the filter.
        self.max_age_s = max_age_s
        self._metrics = registry if registry is not None \
            else reliability_metrics
        self._tracer = tracer
        # THIS host newly flagged -> one triggered device-profile capture
        # (telemetry/profiler.py): the straggling host profiles itself at
        # the moment it deviates. A no-op until a profile dir is
        # configured; rate-limited by the session's own slot; absorbed.
        self.profile_on_flag = bool(profile_on_flag)
        self._flagged: set = set()

    def check(self) -> list:
        """One detection pass; returns the straggler rows (process_id,
        p50, fleet median). Emits `train.straggler` on a host's flag
        TRANSITION (not every pass) and keeps the `train.stragglers`
        gauge current. Never raises — detection is observability."""
        try:
            rows = self.heartbeat.read_all(max_age_s=self.max_age_s)
        except Exception:  # noqa: BLE001 - a torn beat loses one pass
            return []
        p50s = []
        for row in rows:
            stats = row.get("stats") or {}
            p50 = stats.get("step_p50_ms")
            if (isinstance(p50, (int, float)) and p50 > 0.0
                    and stats.get("steps", 0) >= self.min_steps):
                p50s.append((int(row.get("process_id", -1)), float(p50)))
        if len(p50s) < 2:       # a fleet of one has no stragglers
            self._metrics.set_gauge(tnames.TRAIN_STRAGGLERS, 0)
            return []
        ordered = sorted(v for _, v in p50s)
        median = ordered[len(ordered) // 2] if len(ordered) % 2 else \
            0.5 * (ordered[len(ordered) // 2 - 1]
                   + ordered[len(ordered) // 2])
        stragglers = [
            {"process_id": pid, "step_p50_ms": p50,
             "fleet_p50_ms": median, "threshold": self.threshold}
            for pid, p50 in p50s
            if median > 0.0 and p50 > self.threshold * median]
        now_flagged = {s["process_id"] for s in stragglers}
        tracer = self._tracer if self._tracer is not None else get_tracer()
        for s in stragglers:
            if s["process_id"] not in self._flagged:
                tracer.event(tnames.TRAIN_STRAGGLER_EVENT,
                             host=s["process_id"],
                             step_p50_ms=round(s["step_p50_ms"], 3),
                             fleet_p50_ms=round(s["fleet_p50_ms"], 3),
                             threshold=self.threshold)
        own = getattr(self.heartbeat, "process_id", None)
        capture_self = (self.profile_on_flag and own is not None
                        and own in now_flagged and own not in self._flagged)
        self._flagged = now_flagged
        self._metrics.set_gauge(tnames.TRAIN_STRAGGLERS, len(now_flagged))
        if capture_self:
            # flag TRANSITION on this host: capture a device profile of
            # the very steps that are straggling (ordered AFTER the
            # train.straggler event in the span log — the capture's
            # telemetry.profile event seq follows it causally)
            try:
                from .profiler import get_profile_session
                get_profile_session().capture(reason="straggler")
            except Exception:  # noqa: BLE001 - detection must not raise
                pass
        return stragglers


# Process-default clock: what the flight recorder's goodput.json and the
# trainer exposition read when nobody handed them a clock explicitly.
# Mirrors get_tracer()/reliability_metrics: last installed wins (one live
# training loop per process is the overwhelmingly common shape).
_default_clock: Optional[StepClock] = None
_default_lock = threading.Lock()


def install_clock(clock: StepClock) -> StepClock:
    global _default_clock
    with _default_lock:
        _default_clock = clock
    return clock


def get_clock() -> Optional[StepClock]:
    with _default_lock:
        return _default_clock


def default_snapshot() -> dict:
    """The installed clock's snapshot, or {} — safe from any context (the
    flight recorder calls this mid-dump)."""
    clock = get_clock()
    if clock is None:
        return {}
    try:
        return clock.snapshot()
    except Exception:  # noqa: BLE001 - a bundle without goodput beats none
        return {}
