"""Live telemetry regression watcher: threshold + median-shift
change-point detection over `TelemetryPoller` series.

Until now a performance regression was only visible OFFLINE — the next
`benchdiff` round over recorded BENCH files. The fleet poller already
retains the live series (windowed p99s, goodput, queue depth, the new
`op.<region>.*` roofline gauges); this module watches them and turns a
live shift into an incident artifact instead of a post-hoc diff
(docs/observability.md "Live regression watch"):

- **WatchRule**: one watched series key with either/both detectors —
  a *threshold* bound (``max_value`` / ``min_value`` on the latest
  sample) and a *median-shift* change-point (``shift`` factor: the
  median of the last ``window`` samples against the median of the
  ``window`` samples before them; directions ``up``/``down``/``both``).
  Medians, not means — one GC pause must not trip a latency rule.
- **TelemetryWatcher**: evaluates every rule over `poller.series(key)`
  (or an injected ``series`` dict — detection is a pure function of the
  series, so tests drive it deterministically without threads or
  sleeps). A rule's False->True transition emits a
  `telemetry.watch.trip` event, counts `telemetry.watch.trips`, and
  notifies the `FlightRecorder` through its existing per-source latch
  (``source="watch:<key>"``) — a live regression gets a flight bundle
  (and, with ``profile_on_burn``, a device profile), not a post-hoc
  bench diff. Recovery notifies ``burning: False`` so the latch re-arms
  for the next incident. The `telemetry.watch.tripped` gauge holds the
  number of currently-tripped rules.
- Optional background cadence: `start(interval_s)` runs `check()` on a
  daemon thread (Event.wait is the sleep AND the stop signal, the
  poller's own pattern); `stop()` joins it.
"""
from __future__ import annotations

import statistics
import threading
from typing import NamedTuple, Optional

from ..reliability.metrics import reliability_metrics
from . import names as tnames
from .spans import get_tracer


class WatchRule(NamedTuple):
    """One watched series (see module docstring). `key` addresses the
    poller's merged-metric namespace (e.g. ``serving.request.e2e.p99``,
    ``train.goodput``). At least one detector must be configured."""
    key: str
    max_value: Optional[float] = None   # threshold: latest > max trips
    min_value: Optional[float] = None   # threshold: latest < min trips
    shift: Optional[float] = None       # median-shift factor (> 1.0)
    direction: str = "both"             # shift direction: up/down/both
    window: int = 8                     # samples per shift side
    min_samples: int = 4                # below this the rule stays quiet


def evaluate_rule(rule: WatchRule, series: list) -> Optional[dict]:
    """Pure detection: the breach description for `rule` over
    ``[(t, value), ...]``, or None. Deterministic — same series, same
    verdict — so the watcher's behavior is pinned by value tables, not
    sleeps."""
    vals = [float(v) for _, v in series]
    if len(vals) < max(int(rule.min_samples), 1):
        return None
    last = vals[-1]
    if rule.max_value is not None and last > rule.max_value:
        return {"key": rule.key, "kind": "threshold", "value": last,
                "bound": float(rule.max_value), "direction": "up"}
    if rule.min_value is not None and last < rule.min_value:
        return {"key": rule.key, "kind": "threshold", "value": last,
                "bound": float(rule.min_value), "direction": "down"}
    if rule.shift is not None and rule.shift > 0.0:
        w = max(int(rule.window), 2)
        if len(vals) >= 2 * w:
            baseline = statistics.median(vals[-2 * w:-w])
            recent = statistics.median(vals[-w:])
            up = (recent > rule.shift * baseline) if baseline > 0.0 \
                else recent > 0.0
            down = baseline > 0.0 and recent < baseline / rule.shift
            if ((up and rule.direction in ("up", "both"))
                    or (down and rule.direction in ("down", "both"))):
                return {"key": rule.key, "kind": "shift",
                        "value": recent, "baseline": baseline,
                        "factor": float(rule.shift),
                        "direction": "up" if up else "down"}
    return None


class TelemetryWatcher:
    """Rule evaluation + trip-transition bookkeeping over a poller's
    retained series (module docstring)."""

    def __init__(self, poller=None, rules=(), registry=None, tracer=None,
                 recorder=None):
        self.poller = poller
        self.rules = [r if isinstance(r, WatchRule) else WatchRule(**r)
                      for r in rules]
        for r in self.rules:
            if (r.max_value is None and r.min_value is None
                    and r.shift is None):
                raise ValueError(
                    f"rule for {r.key!r} has no detector configured")
        self._metrics = registry if registry is not None \
            else reliability_metrics
        self._tracer = tracer
        self._recorder = recorder
        self._tripped: dict = {}       # rule key -> last breach dict
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._trips_total = 0

    # -- detection ------------------------------------------------------------
    def check(self, series: Optional[dict] = None) -> list:
        """One detection pass; returns the NEW trips (transitions only).
        `series` overrides the poller read per key ({key: [(t, v), ...]})
        — the deterministic test/replay entry point. Never raises:
        watching is observability."""
        trips: list = []
        recoveries: list = []
        tracer = self._tracer if self._tracer is not None else get_tracer()
        for rule in self.rules:
            try:
                s = (series.get(rule.key, []) if series is not None
                     else self.poller.series(rule.key)
                     if self.poller is not None else [])
                breach = evaluate_rule(rule, s)
            except Exception:  # noqa: BLE001 - a torn series loses one pass
                continue
            with self._lock:
                was = rule.key in self._tripped
                if breach is not None:
                    self._tripped[rule.key] = breach
                    if not was:
                        self._trips_total += 1
                else:
                    self._tripped.pop(rule.key, None)
                now_tripped = len(self._tripped)
            if breach is not None and not was:
                trips.append(breach)
                self._metrics.inc(tnames.TELEMETRY_WATCH_TRIPS)
                tracer.event(tnames.TELEMETRY_WATCH_TRIP_EVENT, **breach)
            elif breach is None and was:
                recoveries.append(rule.key)
            self._metrics.set_gauge(tnames.TELEMETRY_WATCH_TRIPPED,
                                    now_tripped)
        # the recorder is a non-SLO flight source: each rule gets its own
        # latch (source="watch:<key>"), trips arm it, recoveries re-arm —
        # a live regression leaves a bundle, not just an event line
        recorder = self._recorder
        if recorder is None:
            try:
                from .perf import get_flight_recorder
                recorder = get_flight_recorder()
            except Exception:  # noqa: BLE001
                recorder = None
        if recorder is not None:
            for breach in trips:
                try:
                    recorder.on_verdict(
                        {"burning": True, "watch": breach},
                        reason=f"watch-{breach['key']}",
                        source=f"watch:{breach['key']}")
                except Exception:  # noqa: BLE001 - never kills the watcher
                    pass
            for key in recoveries:
                try:
                    recorder.on_verdict({"burning": False},
                                        source=f"watch:{key}")
                except Exception:  # noqa: BLE001
                    pass
        return trips

    # -- read side ------------------------------------------------------------
    def tripped(self) -> dict:
        """Currently-tripped rules: {key: last breach dict}."""
        with self._lock:
            return {k: dict(v) for k, v in self._tripped.items()}

    def stats(self) -> dict:
        with self._lock:
            return {"rules": len(self.rules),
                    "tripped": len(self._tripped),
                    "trips_total": self._trips_total,
                    "running": self._thread is not None
                    and self._thread.is_alive()}

    # -- background cadence ---------------------------------------------------
    def start(self, interval_s: float = 30.0) -> "TelemetryWatcher":
        if self._thread is not None:
            raise RuntimeError("watcher already started")
        if interval_s <= 0.0:
            raise ValueError("interval_s must be > 0")
        self._stop.clear()
        self._interval_s = float(interval_s)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-watch")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while True:
            self.check()
            if self._stop.wait(self._interval_s):
                return
