"""Deployment observability: content-addressed model versions, the run
ledger, per-version telemetry splits, and fleet canary verdicts.

The telemetry tier through PR 12 can say the fleet is fast, not burning,
and still predicting well — but not WHICH model any of those signals
describe: `pipeline_fingerprint` deliberately hashes only shapes/dtypes,
so a retrained model with new weights is invisible to every gauge. This
module is the identity-and-comparison layer (ROADMAP item 5's sensor
half):

- **ModelVersion** — a content-addressed identity: the cheap structural
  fingerprint plan-cache keys use, extended with an opt-in fitted-array
  content digest (`pipeline_fingerprint(model, content=True)`, built on
  `utils.checkpoint.array_sha256`) so two fits of the same architecture
  get DIFFERENT versions; plus a lineage record (estimator params,
  reference-profile digest, source checkpoint step, fit goodput/wall)
  the GBDT estimators stamp at fit time.
- **RunLedger** — an append-only JSONL of every fitted version, the
  durable "what did we ever ship" record (env
  `MMLSPARK_TPU_RUN_LEDGER` or `configure_run_ledger(path)`).
- **VersionRegistry** — the process-level serving-side registry
  `ServingTransform.install_model` feeds. Bounded to TWO slots
  (incumbent + candidate): the currently served version is the
  *candidate*, the previous one the *incumbent* whose windowed
  latency/error stats and drift freeze at swap time. Each slot owns its
  own `MetricsRegistry`, so `/versions` answers per-version splits of
  the request histograms without touching the global registry's merge
  discipline.
- **Canary gauges** — `refresh_canary_gauges` publishes
  `canary.p99.ratio` / `canary.error_burn` / `canary.drift.delta`
  comparing the candidate's live telemetry against the incumbent's
  frozen baseline; `slo.canary_objectives()` turns them into burn-rate
  verdicts and `canary_watch_rules()` into watcher trips — the rollback
  *signal*; actuation stays with the control plane (ROADMAP item 3).

Everything here is guarded the same way the quality tier is: lineage
must never fail a fit, and version accounting must never fail a request.
"""
from __future__ import annotations

import json
import os
import threading
from typing import NamedTuple, Optional

from ..reliability.metrics import MetricsRegistry, reliability_metrics
from . import names as tnames
from .spans import wall_now

# keep bounded: incumbent + candidate only (the ISSUE contract); a
# longer history is the RunLedger's job, not the live registry's
MAX_VERSION_SLOTS = 2

# candidate error-budget for canary.error_burn (fraction of requests
# allowed to fail server-side before the gauge reads 1.0 == burning)
DEFAULT_CANARY_ERROR_BUDGET = 0.01


class ModelVersion(NamedTuple):
    """Content-addressed model identity + its fit-time lineage record."""
    version: str                    # short id clients see (X-Model-Version)
    fingerprint: str                # structural digest (plan-cache keys)
    content_digest: Optional[str]   # fitted-array content digest (opt-in)
    lineage: dict                   # JSON-safe fit-time record

    def export(self) -> dict:
        return {"version": self.version, "fingerprint": self.fingerprint,
                "content_digest": self.content_digest,
                "lineage": dict(self.lineage)}


def model_version(model, content: bool = True,
                  lineage: Optional[dict] = None) -> ModelVersion:
    """Build the ModelVersion for a fitted model/pipeline.

    `content=True` (default) hashes the fitted arrays' BYTES, so two
    fits of the same architecture on different data are distinct
    versions — the identity `install_model` swaps on and every reply's
    `X-Model-Version` names. `content=False` falls back to the cheap
    structural digest (identical-architecture fits collide — fine for
    tests that only need A-vs-B). The lineage record the estimators
    stamped on the model (`model.lineage`) rides along; an explicit
    `lineage=` overrides it."""
    from ..io.plan import pipeline_fingerprint   # lazy: io imports telemetry
    fp = pipeline_fingerprint(model)
    digest = pipeline_fingerprint(model, content=True) if content else None
    rec = lineage if lineage is not None else \
        dict(getattr(model, "lineage", None) or {})
    return ModelVersion(version=(digest or fp)[:12], fingerprint=fp,
                        content_digest=digest, lineage=rec)


# ------------------------------------------------------------ run ledger
class RunLedger:
    """Append-only JSONL of fitted model versions: one line per fit,
    written whole (single os.write of one encoded line) so concurrent
    fitters interleave at line granularity, never mid-record."""

    def __init__(self, path: str):
        self.path = path

    def append(self, record: dict) -> None:
        line = json.dumps(record, sort_keys=True,
                          default=str).encode() + b"\n"
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        try:
            os.write(fd, line)
        finally:
            os.close(fd)

    def append_event(self, event: str, **attrs) -> dict:
        """Journal one named control-plane event (rollout transitions,
        promotions, rollbacks) with a wall-clock stamp. File order IS the
        sequence — append is a single O_APPEND write, so a reader can pin
        `deploy < burn < rollback < recovered` by line position alone."""
        record = {"event": event, "t": wall_now(), **attrs}
        self.append(record)
        return record

    def records(self) -> list:
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue   # torn tail line (crashed writer): skip
        return out


_ledger: Optional[RunLedger] = None
_ledger_lock = threading.Lock()


def configure_run_ledger(path: Optional[str]) -> Optional[RunLedger]:
    """Set (or clear, with None) the process run ledger."""
    global _ledger
    with _ledger_lock:
        _ledger = RunLedger(path) if path else None
        return _ledger


def get_run_ledger() -> Optional[RunLedger]:
    """The configured ledger, else one from MMLSPARK_TPU_RUN_LEDGER."""
    with _ledger_lock:
        if _ledger is not None:
            return _ledger
    path = os.environ.get("MMLSPARK_TPU_RUN_LEDGER")
    return RunLedger(path) if path else None


# ------------------------------------------------- the version registry
class _Slot:
    """One tracked version: its identity, its own metrics registry (the
    per-version latency/error split), and — once superseded — the frozen
    baseline the canary gauges compare the candidate against."""

    __slots__ = ("mv", "role", "installed_at", "registry", "frozen")

    def __init__(self, mv: ModelVersion):
        self.mv = mv
        self.role = "candidate"
        self.installed_at = wall_now()
        self.registry = MetricsRegistry()
        self.frozen: Optional[dict] = None

    def baseline(self) -> dict:
        """Snapshot this slot's own stats (taken at swap time to freeze
        the incumbent's baseline)."""
        snap = self.registry.snapshot()
        total = snap.get(tnames.SERVING_REQUEST_TOTAL, 0)
        errors = snap.get(tnames.SERVING_REQUEST_ERRORS, 0)
        return {
            "p99_ms": snap.get(tnames.SERVING_REQUEST_TRANSFORM + ".p99"),
            "p50_ms": snap.get(tnames.SERVING_REQUEST_TRANSFORM + ".p50"),
            "requests": total, "errors": errors,
            "error_rate": (errors / total) if total else 0.0,
            "drift_max": _live_drift_max(),
        }


def _live_drift_max() -> Optional[float]:
    """Current quality.drift scores' max from the live monitor — read
    directly (not via gauges) so freezing works without a scrape."""
    try:
        from . import quality as tquality
        drift = tquality.get_monitor().drift()
        vals = [row.get("psi") for row in drift.values()
                if isinstance(row, dict)]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None
    except Exception:  # noqa: BLE001 - lineage never fails serving
        return None


class VersionRegistry:
    """Process-level registry of the served model versions (bounded:
    incumbent + candidate). `ServingTransform` installs versions and
    feeds per-request observations; `/versions` exports it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slots: "list[_Slot]" = []   # [incumbent?, candidate]

    # -- install / swap ---------------------------------------------------
    def install(self, mv: ModelVersion, metrics=None) -> dict:
        """Track `mv` as the served (candidate) version. The previously
        current slot becomes the incumbent and its stats freeze — the
        canary baseline. Returns {"old": id|None, "new": id}."""
        reg = metrics if metrics is not None else reliability_metrics
        with self._lock:
            cur = self._slots[-1] if self._slots else None
            if cur is not None and cur.mv.version == mv.version:
                return {"old": cur.mv.version, "new": mv.version}
            if cur is not None:
                cur.role = "incumbent"
                cur.frozen = cur.baseline()
            self._slots.append(_Slot(mv))
            del self._slots[:-MAX_VERSION_SLOTS]
            n = len(self._slots)
        reg.set_gauge(tnames.SERVING_MODEL_VERSION_INFO, float(n))
        return {"old": cur.mv.version if cur else None, "new": mv.version}

    def _slot(self, version_id: Optional[str]) -> Optional[_Slot]:
        for s in self._slots:
            if version_id is None or s.mv.version == version_id:
                if version_id is not None or s is self._slots[-1]:
                    return s
        return None

    # -- per-request observation -----------------------------------------
    def observe(self, version_id: str, ms: Optional[float] = None,
                rows: int = 1, errors: int = 0) -> None:
        """Fold one served batch into that version's split registry.
        Unknown versions (a drained plan finishing after its slot aged
        out) are dropped — bounded by design, never raising."""
        with self._lock:
            slot = self._slot(version_id)
        if slot is None:
            return
        if rows:
            slot.registry.inc(tnames.SERVING_REQUEST_TOTAL, rows)
        if errors:
            slot.registry.inc(tnames.SERVING_REQUEST_ERRORS, errors)
        if ms is not None:
            slot.registry.observe_ms(tnames.SERVING_REQUEST_TRANSFORM, ms)

    def current_version(self) -> Optional[str]:
        with self._lock:
            return self._slots[-1].mv.version if self._slots else None

    # -- export / canary --------------------------------------------------
    def export(self, window_s: Optional[float] = None) -> dict:
        """JSON-safe `/versions` payload: every tracked version's
        lineage, role, per-version metric split, and (incumbent) frozen
        baseline, plus the live canary comparison when both exist."""
        with self._lock:
            slots = list(self._slots)
        versions = {}
        for s in slots:
            entry = s.mv.export()
            entry["role"] = s.role
            entry["installed_at"] = s.installed_at
            try:
                entry["metrics"] = s.registry.export_state(
                    window_s=window_s)
            except ValueError:
                entry["metrics"] = s.registry.export_state()
            entry["split"] = s.baseline() if s.frozen is None else None
            entry["frozen"] = s.frozen
            versions[s.mv.version] = entry
        out = {"current": slots[-1].mv.version if slots else None,
               "versions": versions}
        canary = self._canary_values(slots)
        if canary:
            out["canary"] = canary
        return out

    def _canary_values(self, slots,
                       error_budget: float = DEFAULT_CANARY_ERROR_BUDGET
                       ) -> Optional[dict]:
        """Candidate-vs-incumbent comparison, None until a swap has
        produced both a frozen baseline and a live candidate."""
        if len(slots) < 2 or slots[0].frozen is None:
            return None
        cand, base = slots[-1].baseline(), slots[0].frozen
        out: dict = {"candidate": slots[-1].mv.version,
                     "incumbent": slots[0].mv.version}
        if cand["p99_ms"] is not None and base.get("p99_ms"):
            out["p99_ratio"] = cand["p99_ms"] / base["p99_ms"]
        out["error_burn"] = cand["error_rate"] / max(error_budget, 1e-9)
        if cand["drift_max"] is not None:
            out["drift_delta"] = cand["drift_max"] - (
                base.get("drift_max") or 0.0)
        return out

    def refresh_canary_gauges(self, registry=None,
                              error_budget: float =
                              DEFAULT_CANARY_ERROR_BUDGET) -> dict:
        """Publish the canary comparison as gauges (scrape-time refresh,
        like the quality gauges). Gauges stay ABSENT until incumbent +
        candidate both exist: the SLO engine reads absence as no_data,
        burn 0 — a fleet that never swapped can't burn a canary."""
        reg = registry if registry is not None else reliability_metrics
        with self._lock:
            slots = list(self._slots)
        vals = self._canary_values(slots, error_budget=error_budget)
        if not vals:
            return {}
        if "p99_ratio" in vals:
            reg.set_gauge(tnames.CANARY_P99_RATIO, vals["p99_ratio"])
        reg.set_gauge(tnames.CANARY_ERROR_BURN, vals["error_burn"])
        if "drift_delta" in vals:
            reg.set_gauge(tnames.CANARY_DRIFT_DELTA, vals["drift_delta"])
        return vals

    def reset(self) -> None:
        with self._lock:
            self._slots = []


_registry: Optional[VersionRegistry] = None
_registry_lock = threading.Lock()


def get_version_registry() -> VersionRegistry:
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = VersionRegistry()
        return _registry


def reset_version_registry() -> None:
    global _registry
    with _registry_lock:
        _registry = None


# ------------------------------------------------------- module helpers
def export_versions(window_s: Optional[float] = None) -> dict:
    """The process's `/versions` payload (flight bundles embed it as
    versions.json)."""
    return get_version_registry().export(window_s=window_s)


def refresh_canary_gauges(registry=None) -> dict:
    """Scrape-time canary gauge refresh (exposition calls this next to
    the quality refresh; guarded there)."""
    return get_version_registry().refresh_canary_gauges(registry=registry)


def versions_http_response(window_s: Optional[float] = None):
    """(status, body, content_type) for GET /versions."""
    return 200, json.dumps(export_versions(window_s=window_s),
                           default=str).encode(), "application/json"


def merge_version_exports(exports: list) -> dict:
    """Merge per-worker `/versions` payloads fleet-wide: version ids
    union (lineage from any worker — content addressing makes them
    identical), per-version metric splits merge EXACTLY via the same
    `merge_states` discipline the cluster scrape uses (counts sum,
    histogram buckets add), and each version remembers which workers
    currently serve it — the rollout-skew record the poller tracks."""
    from .exposition import merge_states   # lazy: exposition imports slo
    merged: dict = {"versions": {}, "current_by_worker": {}}
    states: dict = {}
    workers: dict = {}
    for name, exp in exports:
        if not isinstance(exp, dict):
            continue
        merged["current_by_worker"][name] = exp.get("current")
        for vid, entry in (exp.get("versions") or {}).items():
            tgt = merged["versions"].setdefault(
                vid, {k: v for k, v in entry.items() if k != "metrics"})
            states.setdefault(vid, []).append(entry.get("metrics") or {})
            workers.setdefault(vid, []).append(name)
            # a version incumbent on one worker and candidate on another
            # is MID-ROLLOUT; candidate (the newer role) wins the merge
            if entry.get("role") == "candidate":
                tgt["role"] = "candidate"
    for vid, sts in states.items():
        try:
            merged["versions"][vid]["metrics"] = merge_states(sts)
        except Exception:  # noqa: BLE001 - a torn worker export can't
            merged["versions"][vid]["metrics"] = {}      # kill the merge
        merged["versions"][vid]["workers"] = sorted(workers[vid])
    return merged


def rollout_skew(current_by_worker: dict) -> dict:
    """Per-version worker counts from a merged export's
    `current_by_worker` map — `{version_id: n_workers}`; more than one
    key means the fleet is mid-rollout (the poller's skew series)."""
    skew: dict = {}
    for ver in current_by_worker.values():
        if ver is not None:
            skew[ver] = skew.get(ver, 0) + 1
    return skew


def canary_watch_rules(p99_ratio_max: float = 2.0,
                       error_burn_max: float = 1.0,
                       drift_delta_max: float = 0.25) -> list:
    """Watch rules over the canary gauges: a candidate 2x slower than
    the incumbent's frozen p99, burning its error budget, or drifting
    past the PSI delta trips the watcher (flight bundle + event) —
    min_samples=1 because each sample is already a full fleet scrape."""
    from .watch import WatchRule
    return [WatchRule(key=tnames.CANARY_P99_RATIO,
                      max_value=p99_ratio_max, min_samples=1),
            WatchRule(key=tnames.CANARY_ERROR_BURN,
                      max_value=error_burn_max, min_samples=1),
            WatchRule(key=tnames.CANARY_DRIFT_DELTA,
                      max_value=drift_delta_max, min_samples=1)]
