"""Device-profile observability: triggered on-device capture, per-op
parse, and per-region roofline attribution.

ROADMAP item 1 made `hbm_utilization` the honesty metric of the
histogram roofline chase, but the tree could only compute it for the
WHOLE fit — 1.8% at BENCH_r05 with nothing able to say which op burns
the other 98%. This module is the fourth observability tier
(docs/observability.md "Device profiling & roofline"): the sensors that
turn "the fit is memory-idle" into "gbdt.hist achieves X% of peak HBM
and gbdt.route none of it" — the per-op (cost-analysis, measured-time)
pairs *A Learned Performance Model for TPUs* (PAPERS.md) trains on and
the ROADMAP item-4 autotuner's measured rows.

- **ProfileSession**: programmatic `jax.profiler` start/stop with the
  flight-recorder discipline — disabled until a profile dir is
  configured (env ``MMLSPARK_TPU_PROFILE_DIR``), min-interval rate
  limiting (`telemetry.profile.suppressed`), bounded retention (oldest
  capture dirs pruned), and failure ROLLBACK (a failed capture gives the
  rate-limit slot back and removes its partial dir, so it can never
  shadow the next trigger). Triggers: `GET /debug/profile?ms=N` (same
  429/503/500 contract as `/debug/bundle`), a `StragglerDetector` flag
  transition on the flagged host, an SLO burn via the recorder latch
  (`FlightRecorder(profile_on_burn=True)`), and `utils.tracing.trace`
  (the explicit block-capture API, rebased on `session()`).
- **parse_trace**: the captured trace (TensorBoard trace-event JSON,
  ``plugins/profile/*/​*.trace.json.gz``) parsed into per-op records
  ``{op, region, occurrences, self_time_us}`` from the DEVICE planes.
  Field-by-field graceful degradation, mirroring `executable_analysis`'s
  never-raise contract: on the CPU backend device planes are absent and
  the table is empty — capture still succeeds, regions still carry their
  host-noted walls. `region` resolves by matching the registered region
  names (`REGIONS`) against op names/metadata — the
  `jax.named_scope`/`TraceAnnotation` stamps the GBDT tree build
  (`gbdt.hist`/`gbdt.split`/`gbdt.route`), `serving.plan.run`, and
  `train.step` now carry.
- **RooflineLedger**: joins per-region measured time (device-plane
  self-time when a parse provided it, host-noted wall otherwise) with
  `CompileLog` cost analysis into achieved FLOP/s and HBM bytes/s
  against peak (env/chip table, `resolve_peaks`). Exported as
  `op.<region>.{hbm_util,flops_util}` gauges, the `roofline.json`
  section of every flight bundle, and the `roofline` block of bench.py's
  headline record. A side that is unknown (no peak declared, no cost
  analysis for the region) leaves its gauge ABSENT — never guessed,
  same contract as MFU.
"""
from __future__ import annotations

import contextlib
import contextvars
import glob
import gzip
import json
import os
import re
import shutil
import sys
import threading
import time
from typing import Optional

from ..reliability.metrics import reliability_metrics
from . import names as tnames
from .spans import get_tracer, wall_now

PROFILE_DIR_ENV = "MMLSPARK_TPU_PROFILE_DIR"
# default capture window for TRIGGERED captures (ms); explicit callers
# and ?ms=N override
PROFILE_MS_ENV = "MMLSPARK_TPU_PROFILE_MS"
PEAK_HBM_ENV = "MMLSPARK_TPU_PEAK_HBM_GBPS"

# Canonical trace-annotation region names: what the parser attributes
# per-op device time to, and the keys of the roofline ledger / the
# op.<region>.* gauges. The GBDT tree build stamps its three phases with
# jax.named_scope (trace-time: the names ride the compiled ops' metadata
# into the device planes); host-side hot paths stamp
# utils.tracing.annotate (TraceAnnotation + host wall note).
REGIONS = ("gbdt.hist", "gbdt.split", "gbdt.route",
           "serving.plan.run", "train.step")

# per-chip peaks (bf16 TFLOP/s, HBM GB/s) keyed on device_kind
# substrings — the StepClock-style fallback when no env override is set.
# Spec-sheet numbers, labeled as such in resolve_peaks()["source"].
CHIP_PEAKS = (
    ("v6e", 918.0, 1640.0),
    ("v5p", 459.0, 2765.0),
    ("v5e", 197.0, 819.0),
    ("v5 lite", 197.0, 819.0),
    ("v4", 275.0, 1228.0),
)

_REASON_RE = re.compile(r"[^a-zA-Z0-9_-]+")

# active region (utils.tracing.annotate sets it): CompileLog.record reads
# it so a compile performed inside a region lands with an exact join key
_region_var: contextvars.ContextVar = contextvars.ContextVar(
    "mmlspark_tpu_region", default=None)


def current_region() -> Optional[str]:
    """The innermost active `utils.tracing.annotate` region, or None."""
    return _region_var.get()


# ---------------------------------------------------------------- peaks
def peak_hbm_from_env() -> Optional[float]:
    """Peak HBM bytes/s from ``MMLSPARK_TPU_PEAK_HBM_GBPS`` (GB/s), or
    None — the documented degrade on hosts that never declared one."""
    raw = os.environ.get(PEAK_HBM_ENV)
    if not raw:
        return None
    try:
        gbps = float(raw)
    except ValueError:
        return None
    return gbps * 1e9 if gbps > 0 else None


def _chip_peaks() -> Optional[tuple]:
    """(flops_per_s, hbm_bytes_per_s, kind) from the local device kind —
    only consulted when jax is ALREADY imported (a passive read must
    never pay a cold jax import), and only for kinds in CHIP_PEAKS."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax
        kind = str(getattr(jax.devices()[0], "device_kind", ""))
    except Exception:  # noqa: BLE001 - no backend: no chip peaks
        return None
    low = kind.lower()
    for token, tflops, gbps in CHIP_PEAKS:
        if token in low:
            return tflops * 1e12, gbps * 1e9, kind
    return None


def resolve_peaks(peaks: Optional[dict] = None) -> dict:
    """{"flops_per_s", "hbm_bytes_per_s", "source"} with explicit args
    > env (``MMLSPARK_TPU_PEAK_TFLOPS`` / ``MMLSPARK_TPU_PEAK_HBM_GBPS``)
    > chip table. A side nobody declared stays None — downstream
    utilization gauges are then absent, never guessed."""
    out = {"flops_per_s": None, "hbm_bytes_per_s": None, "source": None}
    if peaks:
        out["flops_per_s"] = peaks.get("flops_per_s")
        out["hbm_bytes_per_s"] = peaks.get("hbm_bytes_per_s")
        out["source"] = peaks.get("source", "explicit")
        if (out["flops_per_s"] is not None
                and out["hbm_bytes_per_s"] is not None):
            return out
    from .goodput import peak_flops_from_env
    env_flops = peak_flops_from_env()
    env_hbm = peak_hbm_from_env()
    if out["flops_per_s"] is None and env_flops is not None:
        out["flops_per_s"] = env_flops
        out["source"] = out["source"] or "env"
    if out["hbm_bytes_per_s"] is None and env_hbm is not None:
        out["hbm_bytes_per_s"] = env_hbm
        out["source"] = out["source"] or "env"
    if out["flops_per_s"] is None or out["hbm_bytes_per_s"] is None:
        chip = _chip_peaks()
        if chip is not None:
            if out["flops_per_s"] is None:
                out["flops_per_s"] = chip[0]
            if out["hbm_bytes_per_s"] is None:
                out["hbm_bytes_per_s"] = chip[1]
            out["source"] = out["source"] or f"chip-table:{chip[2]}"
    return out


# ----------------------------------------------------------- trace parse
_MAX_OP_RECORDS = 512


def _trace_files(log_dir: str) -> list:
    """The capture's ``*.trace.json.gz`` files, newest profile run first
    (jax writes ``plugins/profile/<timestamp>/<host>.trace.json.gz``)."""
    runs = sorted(glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*")), reverse=True)
    for run in runs:
        files = sorted(glob.glob(os.path.join(run, "*.trace.json.gz")))
        if files:
            return files
    return []


def _region_of(name: str, args: Optional[dict]) -> str:
    """First registered region token found in the op name or its string
    metadata (named_scope paths ride `long_name`-style args on TPU
    planes); 'other' when none match."""
    for region in REGIONS:
        if region in name:
            return region
    if args:
        for v in args.values():
            if isinstance(v, str):
                for region in REGIONS:
                    if region in v:
                        return region
    return "other"


def parse_trace(log_dir: str) -> list:
    """Per-op records from a captured profile's DEVICE planes:
    ``[{op, region, occurrences, self_time_us}]``, largest self-time
    first, bounded. NEVER raises (the `executable_analysis` contract):
    a missing/torn trace file, an unexpected schema, or a backend with
    no device planes (CPU) all degrade to an empty table field by
    field."""
    ops: dict = {}
    for path in _trace_files(log_dir):
        try:
            with gzip.open(path, "rt") as f:
                obj = json.load(f)
        except Exception:  # noqa: BLE001 - torn capture: skip the file
            continue
        events = obj.get("traceEvents") if isinstance(obj, dict) else None
        if not isinstance(events, list):
            continue
        device_pids = set()
        for e in events:
            if not isinstance(e, dict) or e.get("ph") != "M":
                continue
            if e.get("name") != "process_name":
                continue
            pname = str((e.get("args") or {}).get("name", ""))
            # device planes are named "/device:TPU:0 ..." (the CPU
            # backend exposes only "/host:CPU" — no device plane, empty
            # table, the documented degrade)
            if pname.startswith("/device:"):
                device_pids.add(e.get("pid"))
        if not device_pids:
            continue
        for e in events:
            if not isinstance(e, dict) or e.get("ph") != "X":
                continue
            if e.get("pid") not in device_pids:
                continue
            name = str(e.get("name", ""))
            dur = e.get("dur")
            if not isinstance(dur, (int, float)):
                continue
            args = e.get("args") if isinstance(e.get("args"), dict) else None
            key = (name, _region_of(name, args))
            ent = ops.get(key)
            if ent is None:
                ops[key] = ent = {"op": name, "region": key[1],
                                  "occurrences": 0, "self_time_us": 0.0}
            ent["occurrences"] += 1
            ent["self_time_us"] += float(dur)
    records = sorted(ops.values(),
                     key=lambda r: (-r["self_time_us"], r["op"]))
    for r in records:
        r["self_time_us"] = round(r["self_time_us"], 3)
    return records[:_MAX_OP_RECORDS]


def region_totals(records: list) -> dict:
    """{region: {"self_time_us", "occurrences"}} rollup of a per-op
    table (what the ledger ingests after a capture)."""
    out: dict = {}
    for r in records:
        ent = out.setdefault(r.get("region", "other"),
                             {"self_time_us": 0.0, "occurrences": 0})
        ent["self_time_us"] += float(r.get("self_time_us", 0.0))
        ent["occurrences"] += int(r.get("occurrences", 0))
    return out


# -------------------------------------------------------- roofline ledger
class RooflineLedger:
    """Per-region achieved-vs-peak accounting (module docstring).

    Two measurement sources feed it: `note_region` (host wall from
    `utils.tracing.annotate` — exists on every backend) and `ingest_ops`
    (device-plane self time from a parsed capture — overrides the host
    wall for regions it covers, labeled ``source: device``). Costs join
    per region from the CompileLog (records whose ``region`` tag or
    label matches) or explicitly via `set_cost` (bench's analytic
    traffic). All state is bounded: regions are a handful of names, ops
    keep the last parse only."""

    def __init__(self, registry=None, compile_log=None,
                 peaks: Optional[dict] = None):
        self._registry = registry
        self._compile_log = compile_log
        self._peaks = peaks
        self._lock = threading.Lock()
        self._host: dict = {}     # region -> [seconds, occurrences]
        self._device: dict = {}   # region -> {"self_time_us", "occurrences"}
        self._ops: list = []      # last parsed per-op table (bounded)
        self._costs: dict = {}    # region -> {"flops", "bytes_accessed"}

    # -- measurement feeds ---------------------------------------------------
    def note_region(self, region: str, seconds: float,
                    occurrences: int = 1, source: str = "host") -> None:
        """Accumulate wall-clock region time measured OUTSIDE a device
        plane. `source` labels the provenance honestly ("host" for
        annotate walls, bench passes "bench-phase" for its in-graph
        phase programs); device-plane self time from a parse overrides
        these rows entirely."""
        s = max(float(seconds), 0.0)
        with self._lock:
            ent = self._host.setdefault(region, [0.0, 0, str(source)])
            ent[0] += s
            ent[1] += int(occurrences)
            ent[2] = str(source)

    def ingest_ops(self, records: list) -> None:
        """Adopt a parsed per-op table: device-plane region totals
        REPLACE earlier device totals (a capture is a fresh window, not
        a cumulative series)."""
        totals = region_totals(records)
        totals.pop("other", None)
        with self._lock:
            self._ops = list(records)
            if totals:
                self._device = totals

    def set_cost(self, region: str, flops: Optional[float] = None,
                 bytes_accessed: Optional[float] = None) -> None:
        """Declare a region's PER-OCCURRENCE cost explicitly (bench's
        analytic histogram traffic; a caller that knows its executable's
        cost analysis). None leaves that side unknown."""
        with self._lock:
            ent = self._costs.setdefault(region, {})
            if flops is not None:
                ent["flops"] = float(flops)
            if bytes_accessed is not None:
                ent["bytes_accessed"] = float(bytes_accessed)

    def clear(self) -> None:
        with self._lock:
            self._host.clear()
            self._device.clear()
            self._costs.clear()
            self._ops = []

    # -- the join ------------------------------------------------------------
    def _cost_of(self, region: str) -> Optional[dict]:
        # explicit declarations win; else the newest compile record
        # tagged with (or labeled as) the region — an exact join key,
        # not a guessed prefix match
        cost = self._costs.get(region)
        if cost:
            return dict(cost)
        log = self._compile_log
        if log is None:
            from .perf import get_compile_log
            log = get_compile_log()
        for rec in reversed(log.records()):
            if rec.get("region") != region and rec.get("label") != region:
                continue
            analysis = rec.get("analysis") or {}
            out = {}
            for field in ("flops", "bytes_accessed"):
                v = analysis.get(field)
                if isinstance(v, (int, float)) and v > 0:
                    out[field] = float(v)
            if out:
                return out
        return None

    def rows(self, peaks: Optional[dict] = None) -> dict:
        """{region: row} with measured seconds/occurrences (+source),
        per-occurrence cost when known, achieved FLOP/s and HBM bytes/s,
        and utilizations when the matching peak is known. Absent keys ARE
        the degrade — a consumer must not find a guessed 0.0."""
        resolved = resolve_peaks(peaks if peaks is not None else self._peaks)
        with self._lock:
            host = {k: list(v) for k, v in self._host.items()}
            device = {k: dict(v) for k, v in self._device.items()}
            costs_known = set(self._costs)
        out: dict = {}
        for region in sorted(set(host) | set(device) | costs_known):
            if region in device:
                seconds = device[region]["self_time_us"] / 1e6
                occurrences = device[region]["occurrences"]
                source = "device"
            elif region in host:
                seconds, occurrences, source = host[region]
            else:
                continue   # a cost with no measurement yet: nothing to say
            row = {"seconds": round(seconds, 6),
                   "occurrences": int(occurrences), "source": source}
            cost = self._cost_of(region)
            if cost and seconds > 0.0:
                for field, achieved_key, peak_key, util_key in (
                        ("flops", "achieved_flops_per_s", "flops_per_s",
                         "flops_util"),
                        ("bytes_accessed", "achieved_hbm_bytes_per_s",
                         "hbm_bytes_per_s", "hbm_util")):
                    per_occ = cost.get(field)
                    if per_occ is None:
                        continue
                    row[field] = per_occ
                    achieved = per_occ * occurrences / seconds
                    row[achieved_key] = round(achieved, 1)
                    peak = resolved.get(peak_key)
                    if peak:
                        # 9 decimals: a genuinely tiny utilization (a
                        # long host wall over a fast chip, ~1e-8) must
                        # not round to a 0.0 that reads as guessed
                        row[util_key] = round(achieved / peak, 9)
            out[region] = row
        return out

    def publish(self, registry=None) -> dict:
        """Set the `op.<region>.{hbm_util,flops_util}` gauges for every
        region whose utilization is computable; absent sides set
        nothing. Returns the rows it published from."""
        reg = registry if registry is not None else (
            self._registry if self._registry is not None
            else reliability_metrics)
        rows = self.rows()
        for region, row in rows.items():
            if "hbm_util" in row:
                reg.set_gauge(tnames.op_hbm_util(region), row["hbm_util"])
            if "flops_util" in row:
                reg.set_gauge(tnames.op_flops_util(region),
                              row["flops_util"])
        return rows

    def export(self) -> dict:
        """The roofline.json body: peaks (with provenance), per-region
        rows, and the last parsed per-op table."""
        with self._lock:
            ops = list(self._ops)
        return {"t": wall_now(),
                "peaks": resolve_peaks(self._peaks),
                "regions": self.rows(),
                "ops": ops}


_default_ledger = RooflineLedger()


def get_roofline() -> RooflineLedger:
    return _default_ledger


def note_region(region: str, seconds: float) -> None:
    """Host-wall region note into the process-default ledger
    (`utils.tracing.annotate` calls this on every region exit)."""
    _default_ledger.note_region(region, seconds)


@contextlib.contextmanager
def region(name: str):
    """Activate `name` as the current region for the block (compile
    records made inside tag themselves with it) — the contextvar half of
    `utils.tracing.annotate`, split out so the profiler owns the key."""
    token = _region_var.set(name)
    try:
        yield
    finally:
        _region_var.reset(token)


def roofline_export() -> dict:
    """The default ledger's export — what FlightRecorder.dump writes as
    roofline.json. Never raises (a bundle without roofline beats no
    bundle)."""
    try:
        return _default_ledger.export()
    except Exception:  # noqa: BLE001
        return {}


def _stamp_context(log_dir: str, ctx, registry=None) -> bool:
    """Stamp a profile dir with the active trace id
    (`trace_context.json`) so the on-disk artifact and the span log
    cross-reference each other. The capture outranks the stamp — but the
    old silent `pass` on failure hid real breakage, so a failed stamp is
    counted under `telemetry.profile.stamp_errors`."""
    reg = registry if registry is not None else reliability_metrics
    try:
        with open(os.path.join(log_dir, "trace_context.json"), "w") as f:
            json.dump({"trace_id": ctx.trace_id,
                       "span_id": ctx.span_id}, f)
        return True
    except OSError:
        reg.inc(tnames.TELEMETRY_PROFILE_STAMP_ERRORS)
        return False


# --------------------------------------------------------- ProfileSession
class ProfileSession:
    """Rate-limited, bounded, rollback-safe device-profile capture
    (module docstring). Disabled (every trigger a cheap no-op / 503)
    until a profile dir is configured via env ``MMLSPARK_TPU_PROFILE_DIR``
    or `configure(profile_dir=...)`; `utils.tracing.trace` passes an
    explicit log_dir + force=True and works regardless."""

    def __init__(self, profile_dir: Optional[str] = None,
                 min_interval_s: float = 60.0, max_profiles: int = 4,
                 max_ms: float = 10_000.0, registry=None, tracer=None,
                 ledger: Optional[RooflineLedger] = None):
        if profile_dir is None:
            profile_dir = os.environ.get(PROFILE_DIR_ENV) or None
        self.profile_dir = profile_dir
        self.min_interval_s = float(min_interval_s)
        self.max_profiles = max(int(max_profiles), 1)
        self.max_ms = float(max_ms)
        self._registry = registry
        self._tracer = tracer
        self._ledger = ledger
        self._lock = threading.Lock()
        self._seq = 0
        self._last: Optional[float] = None

    @property
    def enabled(self) -> bool:
        return self.profile_dir is not None

    def configure(self, profile_dir=None,
                  min_interval_s: Optional[float] = None,
                  max_profiles: Optional[int] = None,
                  max_ms: Optional[float] = None) -> "ProfileSession":
        """Reconfigure in place (None leaves a knob untouched; pass
        profile_dir="" to disable)."""
        with self._lock:
            if profile_dir is not None:
                self.profile_dir = profile_dir or None
            if min_interval_s is not None:
                self.min_interval_s = float(min_interval_s)
            if max_profiles is not None:
                self.max_profiles = max(int(max_profiles), 1)
            if max_ms is not None:
                self.max_ms = float(max_ms)
        return self

    def default_ms(self) -> float:
        """Capture window for triggered captures (straggler flags, burn
        latches): env ``MMLSPARK_TPU_PROFILE_MS``, default 200, clamped
        to max_ms."""
        raw = os.environ.get(PROFILE_MS_ENV)
        try:
            ms = float(raw) if raw else 200.0
        except ValueError:
            ms = 200.0
        return min(max(ms, 1.0), self.max_ms)

    # -- the capture primitive -----------------------------------------------
    @contextlib.contextmanager
    def session(self, reason: str = "trace",
                log_dir: Optional[str] = None, force: bool = False,
                create_perfetto_link: bool = False):
        """Capture a device profile around the enclosed block; yields an
        info dict that gains ``ops``/``regions``/``path`` at exit.

        One capture path for every entry point: rate-limit gate (skipped
        with force=True — the explicit `utils.tracing.trace` API keeps
        its unconditional behavior), `device.profile` span, the
        trace-context stamp (`trace_context.json`, stamp failures
        counted under `telemetry.profile.stamp_errors`), per-op parse,
        ledger feed, retention pruning. A suppressed capture yields
        ``{"suppressed": True}`` and runs the block unprofiled; a FAILED
        capture rolls the rate-limit slot back, removes the partial
        capture dir (never a caller-owned log_dir), and raises."""
        reg = self._registry if self._registry is not None \
            else reliability_metrics
        own_dir = log_dir is None
        if own_dir and not self.enabled:
            raise RuntimeError(
                "ProfileSession disabled — set MMLSPARK_TPU_PROFILE_DIR "
                "or configure(profile_dir=...)")
        now = time.monotonic()
        with self._lock:
            if (not force and self._last is not None
                    and now - self._last < self.min_interval_s):
                suppressed = True
                prev_last = seq = None
            else:
                suppressed = False
                prev_last = self._last
                self._last = now
                seq = self._seq
                self._seq += 1
        if suppressed:
            reg.inc(tnames.TELEMETRY_PROFILE_SUPPRESSED)
            yield {"suppressed": True}
            return
        tag = _REASON_RE.sub("-", str(reason))[:48] or "profile"
        if own_dir:
            log_dir = os.path.join(self.profile_dir,
                                   f"profile-{os.getpid()}-{seq:04d}-{tag}")
        tracer = self._tracer if self._tracer is not None else get_tracer()
        info = {"path": log_dir, "reason": str(reason), "tag": tag,
                "t": wall_now()}
        started = False
        span = None

        def _rollback():
            # a failed capture must not shadow the next trigger for
            # min_interval_s, keep a partial dir in the retention
            # budget, or leak an unfinished span — same contract on the
            # block path AND the finalization path (stop_trace can fail
            # on a full disk)
            if span is not None:
                span.finish(error="capture-failed")
            with self._lock:
                if self._last == now:
                    self._last = prev_last
            if own_dir:
                shutil.rmtree(log_dir, ignore_errors=True)

        try:
            import jax
            os.makedirs(log_dir, exist_ok=True)
            span = tracer.start_span(tnames.DEVICE_PROFILE_SPAN,
                                     attrs={"log_dir": log_dir})
            jax.profiler.start_trace(
                log_dir, create_perfetto_link=create_perfetto_link)
            started = True
            yield info
        except BaseException:
            if started:
                try:
                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 - already torn down
                    pass
            _rollback()
            raise
        try:
            jax.profiler.stop_trace()
            ctx = span.context if span is not None else tracer.current()
            if ctx is not None:
                _stamp_context(log_dir, ctx, reg)
            ops = parse_trace(log_dir)
            info["ops"] = ops
            info["regions"] = region_totals(ops)
            ledger = self._ledger if self._ledger is not None \
                else _default_ledger
            ledger.ingest_ops(ops)
            ledger.publish(registry=reg)
        except BaseException:
            _rollback()
            raise
        if span is not None:
            span.finish(ops=len(ops))
        if own_dir:
            self._prune()
        reg.inc(tnames.TELEMETRY_PROFILE_CAPTURES)
        tracer.event(tnames.TELEMETRY_PROFILE_EVENT, reason=str(reason),
                     path=log_dir, ops=len(ops))

    def capture(self, ms: Optional[float] = None,
                reason: str = "on-demand",
                force: bool = False) -> Optional[dict]:
        """Timed capture: profile for `ms` (clamped to max_ms) and return
        the manifest, or None when the rate limit suppressed it. Same
        trigger contract as `FlightRecorder.dump`: /debug/profile maps
        None to 429, disabled to 503, and a raised failure to 500."""
        if not self.enabled:
            return None
        if ms is None:
            ms = self.default_ms()
        ms = min(max(float(ms), 1.0), self.max_ms)
        with self.session(reason=reason, force=force) as info:
            if info.get("suppressed"):
                return None
            time.sleep(ms / 1000.0)
        info["ms"] = ms
        return info

    def _prune(self) -> None:
        """Keep the newest `max_profiles` capture dirs (mtime order);
        best-effort — losing a race to a concurrent prune is harmless."""
        try:
            entries = [os.path.join(self.profile_dir, e)
                       for e in os.listdir(self.profile_dir)
                       if e.startswith("profile-")]
            entries.sort(key=lambda p: (os.path.getmtime(p), p))
            for stale in entries[:-self.max_profiles]:
                shutil.rmtree(stale, ignore_errors=True)
        except OSError:
            pass


_session: Optional[ProfileSession] = None
_session_lock = threading.Lock()


def get_profile_session() -> ProfileSession:
    global _session
    with _session_lock:
        if _session is None:
            _session = ProfileSession()
        return _session


def configure_profile_session(**kwargs) -> ProfileSession:
    """Configure the process-default profile session (see
    `ProfileSession.configure`)."""
    return get_profile_session().configure(**kwargs)


def capture_profile(ms: Optional[float] = None, reason: str = "manual",
                    force: bool = False) -> Optional[dict]:
    """One-liner timed capture on the process-default session (the
    public application API; triggers use the same path)."""
    return get_profile_session().capture(ms=ms, reason=reason, force=force)
