"""Bench-trajectory differ: per-metric deltas across BENCH round files.

The driver records one ``BENCH_rNN.json`` per round (a wrapper object
whose ``parsed`` field holds the headline JSON line and whose ``tail``
holds every JSON line the bench printed), but nothing in the tree ever
*compared* rounds — a 20% regression between r4 and r5 was only visible
to a human reading two files. This is the missing tool:

    python -m mmlspark_tpu.telemetry.benchdiff BENCH_r*.json
    python -m mmlspark_tpu.telemetry.benchdiff --threshold 0.15 BENCH_r*.json

prints, per metric, the value trajectory across rounds and the
last-vs-previous delta, and — with ``--threshold`` set — exits nonzero
when any metric regressed by more than that fraction (higher-is-better
by default; flag lower-is-better metrics with ``--lower-better``, e.g.
elapsed-seconds metrics). Accepts the driver wrapper format, raw bench
JSONL (one ``{"metric": ...}`` object per line), or a single JSON
object; rounds order by the wrapper's ``n`` when present, else by
filename.

GBDT regression gates (round 6): every ``gbdt_train_rows_iters_per_sec``
record additionally synthesizes per-shape derived records
``gbdt.<shape>.vs_baseline`` and ``gbdt.<shape>.hbm_utilization`` (both
higher-is-better), so the headline's baseline ratio and the honesty
metric gate across rounds exactly like the MULTICHIP bubble/traffic
records — a kernel "win" that tanked either fails the diff:

    python -m mmlspark_tpu.telemetry.benchdiff --threshold 0.1 BENCH_r*.json

Fleet control-loop gates (round 16): every ``fleet_req_per_sec`` record
(BENCH_MODE=fleet — loadgen through the weighted router with a poison
candidate auto-rolled-back mid-run) additionally synthesizes
``fleet.rollback_window_p99_ms`` and ``fleet.requests_dropped``, both
born ``lower_better`` — a round that stretched the chaos-window tail or
dropped even one request during rollback fails the diff regardless of
throughput.

Online-learning gates (round 17): every ``online_sparse_req_per_sec``
record (BENCH_MODE=online — the sparse-pair serving fast path with the
continuous-learning loop driven through a seeded covariate shift)
additionally synthesizes ``online.updates_per_sec`` (higher-is-better:
the fixed-bucket `partial_fit` throughput) plus ``online.adapt_latency_s``
and ``online.requests_dropped`` (both born ``lower_better`` — the
shift-to-promoted window must not stretch, and a drop during the swap is
a regression even if raw req/s improved).

Backend gating (round 11): records carry a ``backend`` annotation (from
the record itself, or a round file's top-level ``backend`` declaration —
bench.py stamps ``jax.default_backend()``); records measured on a
non-TPU backend are excluded from both trajectories and gates and
reported as excluded — BENCH_EXTRA_r06 is CPU-only (route fallback
``xla``) and must not read as a perf datapoint. BENCH_EXTRA-style
artifacts (records nested as top-level values) are harvested too.

It also reads the ``MULTICHIP_r0N.json`` wrapper format (a driver
object whose ``tail`` holds ``GPIPE_MSWEEP {json}`` / ``TRAFFIC
{json}`` lines): the GPipe microbatch sweep becomes
``gpipe_m<M>_{s_per_step,bubble_fraction}`` records and the collective
account becomes ``comm.<program>.<kind>.{ops,bytes}`` records — all
marked lower-is-better on the record itself (``"lower_better": true``),
so bubble-fraction and collective-bytes trajectories gate exactly like
BENCH_rNN metrics:

    python -m mmlspark_tpu.telemetry.benchdiff --threshold 0.1 \\
        MULTICHIP_r*.json
"""
from __future__ import annotations

import argparse
import json
import re
import sys
from typing import List, Optional, Tuple

_DIGITS = re.compile(r"(\d+)")
# MULTICHIP tail lines: an UPPERCASE tag followed by one JSON object
# (the dryrun prints "GPIPE_MSWEEP {...}" and "TRAFFIC {...}")
_TAGGED = re.compile(r"^([A-Z][A-Z0-9_]*)\s+(\{.*)$")


def _natural_key(path: str) -> tuple:
    """Filename sort key with digit runs compared numerically, so
    BENCH_r10 orders after BENCH_r2 (lexicographic sorting would put it
    first and make last-vs-prev compare the wrong rounds)."""
    return tuple(int(part) if part.isdigit() else part
                 for part in _DIGITS.split(path))


def _sweep_records(sweep: dict) -> list:
    """GPIPE_MSWEEP -> per-M records. Both step time and bubble fraction
    regress by GROWING, so they are born lower-is-better."""
    records = []
    for m in sorted(sweep, key=str):
        entry = sweep[m]
        if not isinstance(entry, dict):
            continue
        for field in ("s_per_step", "bubble_fraction"):
            v = entry.get(field)
            if isinstance(v, (int, float)):
                records.append({"metric": f"gpipe_m{m}_{field}",
                                "value": float(v), "lower_better": True})
    return records


def _traffic_records(table: dict) -> list:
    """TRAFFIC -> per-(program, collective-kind) records. Growing
    collective volume is the regression the voting/bucketing designs
    exist to prevent, so ops and bytes are lower-is-better."""
    records = []
    for prog in sorted(table):
        kinds = table[prog]
        if not isinstance(kinds, dict):
            continue
        for kind in sorted(kinds):
            ent = kinds[kind]
            if not isinstance(ent, dict):
                continue
            for field in ("ops", "bytes"):
                v = ent.get(field)
                if isinstance(v, (int, float)):
                    records.append(
                        {"metric": f"comm.{prog}.{kind}.{field}",
                         "value": float(v), "lower_better": True})
    return records


def _tagged_records(tag: str, obj: dict) -> list:
    """Records synthesized from one tagged tail line (MULTICHIP rounds)."""
    if tag == "GPIPE_MSWEEP" and isinstance(obj.get("sweep"), dict):
        return _sweep_records(obj["sweep"])
    if tag == "TRAFFIC":
        return _traffic_records(obj)
    return []


# extra numeric fields of the GBDT headline record that gate like
# first-class metrics (higher is better for both: vs_baseline IS the
# headline ratio, hbm_utilization is the honesty metric a fake win tanks)
_GBDT_METRIC = "gbdt_train_rows_iters_per_sec"
_GBDT_GATED_FIELDS = ("vs_baseline", "hbm_utilization")


def _gbdt_records(rec: dict) -> list:
    """Derived per-shape gate records from one GBDT headline record. The
    shape rides in the metric name so the wide rows (same metric string,
    earlier tail lines) gate independently of the canonical 8M headline
    instead of being last-line-overwritten. The parent's backend
    annotation rides along — a CPU-only round's derived gates are
    excluded exactly like its headline."""
    if rec.get("metric") != _GBDT_METRIC:
        return []
    tag = str(rec.get("shape", "headline")).replace(" ", "_") or "headline"
    out = []
    for field in _GBDT_GATED_FIELDS:
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            d = {"metric": f"gbdt.{tag}.{field}", "value": float(v)}
            if rec.get("backend") is not None:
                d["backend"] = rec["backend"]
            out.append(d)
    return out


# fields of the BENCH_MODE=fleet headline record that gate as first-class
# LOWER-IS-BETTER metrics: the chaos window's tail latency and the
# zero-drop acceptance count (any value above 0 is a regression, and a
# round that drops requests must fail the diff even if req/s improved)
_FLEET_METRIC = "fleet_req_per_sec"
_FLEET_LOWER_FIELDS = ("rollback_window_p99_ms", "requests_dropped")


def _fleet_records(rec: dict) -> list:
    """Derived gate records from one fleet-bench headline record (born
    ``lower_better``); the parent's backend annotation rides along."""
    if rec.get("metric") != _FLEET_METRIC:
        return []
    out = []
    for field in _FLEET_LOWER_FIELDS:
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            d = {"metric": f"fleet.{field}", "value": float(v),
                 "lower_better": True}
            if rec.get("backend") is not None:
                d["backend"] = rec["backend"]
            out.append(d)
    return out


# fields of the BENCH_MODE=elastic headline record (kill-one-host run)
# that gate as first-class LOWER-IS-BETTER metrics: how long the
# survivors take to resume after the death verdict, and the fraction of
# finished boosting work the committed fleet manifest failed to preserve
_ELASTIC_METRIC = "elastic_detect_s"
_ELASTIC_LOWER_FIELDS = ("resume_s", "lost_work_fraction")


def _elastic_records(rec: dict) -> list:
    """Derived gate records from one elastic-bench headline record (born
    ``lower_better``); the parent's backend annotation rides along."""
    if rec.get("metric") != _ELASTIC_METRIC:
        return []
    out = []
    for field in _ELASTIC_LOWER_FIELDS:
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            d = {"metric": f"elastic.{field}", "value": float(v),
                 "lower_better": True}
            if rec.get("backend") is not None:
                d["backend"] = rec["backend"]
            out.append(d)
    return out


# fields of the BENCH_MODE=workloads headline (iforest + SAR closed-loop
# serving A/B) that gate as first-class per-workload metrics: compiled-path
# throughput (higher better) and its tail latency (born lower-is-better)
_WORKLOADS_METRIC = "workloads_req_per_sec"
_WORKLOADS_HIGHER_FIELDS = ("iforest_req_per_sec", "sar_req_per_sec")
_WORKLOADS_LOWER_FIELDS = ("iforest_p99_ms", "sar_p99_ms")


def _workloads_records(rec: dict) -> list:
    """Derived gate records from one workloads-bench headline record —
    ``workloads.iforest.*`` / ``workloads.sar.*`` so each workload's
    throughput and tail gate independently of the combined headline; the
    parent's backend annotation rides along."""
    if rec.get("metric") != _WORKLOADS_METRIC:
        return []
    out = []
    for field, lower in ([(f, False) for f in _WORKLOADS_HIGHER_FIELDS]
                         + [(f, True) for f in _WORKLOADS_LOWER_FIELDS]):
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            workload, metric = field.split("_", 1)
            d = {"metric": f"workloads.{workload}.{metric}",
                 "value": float(v)}
            if lower:
                d["lower_better"] = True
            if rec.get("backend") is not None:
                d["backend"] = rec["backend"]
            out.append(d)
    return out


# fields of the BENCH_MODE=online headline that gate as first-class
# metrics: partial_fit throughput (higher better) and the self-healing
# window + zero-drop acceptance (born lower-is-better)
_ONLINE_METRIC = "online_sparse_req_per_sec"
_ONLINE_HIGHER_FIELDS = ("online_updates_per_sec",)
_ONLINE_LOWER_FIELDS = ("adapt_latency_s", "requests_dropped")


def _online_records(rec: dict) -> list:
    """Derived gate records from one online-bench headline record; the
    parent's backend annotation rides along."""
    if rec.get("metric") != _ONLINE_METRIC:
        return []
    out = []
    for field, lower in ([(f, False) for f in _ONLINE_HIGHER_FIELDS]
                         + [(f, True) for f in _ONLINE_LOWER_FIELDS]):
        v = rec.get(field)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            d = {"metric": f"online.{field.removeprefix('online_')}",
                 "value": float(v)}
            if lower:
                d["lower_better"] = True
            if rec.get("backend") is not None:
                d["backend"] = rec["backend"]
            out.append(d)
    return out


def _with_derived(records: list) -> list:
    return records + [d for r in records
                      for d in (_gbdt_records(r) + _fleet_records(r)
                                + _online_records(r)
                                + _elastic_records(r)
                                + _workloads_records(r))]


def _records_from_text(text: str) -> list:
    """Every JSON object with a "metric" key found in `text` (whole-file
    object, wrapper with parsed/tail, or JSONL), plus records synthesized
    from MULTICHIP-style tagged tail lines."""
    text = text.strip()
    if not text:
        return []
    records: list = []
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict):
        if "metric" in obj:
            return _with_derived([obj])
        # driver wrapper: {"n": ..., "parsed": {...}, "tail": "..."} —
        # harvest every bench line from the tail (multi-mode runs print
        # several), with `parsed` as the authoritative headline. The
        # MULTICHIP wrapper's tail carries TAGGED lines instead.
        # BENCH_EXTRA-style artifacts nest whole records as top-level
        # values (and declare the round's backend at top level) — harvest
        # those too so an auto-emitted CPU round is SEEN and then
        # excluded from gating by its backend, rather than invisible.
        for v in obj.values():
            if isinstance(v, dict) and "metric" in v:
                records.append(dict(v))
            elif isinstance(v, list):
                records.extend(dict(e) for e in v
                               if isinstance(e, dict) and "metric" in e)
        for line in str(obj.get("tail", "")).splitlines():
            line = line.strip()
            tagged = _TAGGED.match(line)
            if tagged:
                try:
                    payload = json.loads(tagged.group(2))
                except ValueError:
                    continue
                if isinstance(payload, dict):
                    records.extend(_tagged_records(tagged.group(1),
                                                   payload))
                continue
            if line.startswith("{"):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "metric" in rec:
                    records.append(rec)
        # a round-level backend declaration annotates every record that
        # didn't carry its own (newer bench records do) — the per-record
        # field is what gating reads. Annotation runs BEFORE derivation
        # (derived gate records inherit from their parent) and applies
        # to the authoritative `parsed` headline too — the re-added
        # parsed copy below would otherwise gate as TPU.
        file_backend = obj.get("backend")

        def _annotated(rs: list) -> list:
            if isinstance(file_backend, str):
                for r in rs:
                    r.setdefault("backend", file_backend)
            return rs

        # derive BEFORE the parsed-headline dedup: the wide GBDT rows
        # share the headline's metric string and would be dropped by it,
        # but their per-shape derived gate records must survive
        records = _with_derived(_annotated(records))
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            records = [r for r in records
                       if r.get("metric") != parsed["metric"]]
            records.extend(_with_derived(_annotated([dict(parsed)])))
        return records
    # JSONL fallback
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            records.append(rec)
    return _with_derived(records)


def load_round(path: str) -> Tuple[object, dict]:
    """(sort_key, {metric: record}) for one round file."""
    with open(path) as f:
        text = f.read()
    sort_key: object = path
    try:
        obj = json.loads(text)
        if isinstance(obj, dict) and isinstance(obj.get("n"), int):
            sort_key = obj["n"]
    except ValueError:
        pass
    by_metric = {}
    for rec in _records_from_text(text):
        by_metric[rec["metric"]] = rec   # last line wins, like the driver
    return sort_key, by_metric


def _perf_backend(rec: dict) -> bool:
    """Is this record a perf-trajectory datapoint? Records ANNOTATED with
    a non-TPU backend (bench.py stamps `jax.default_backend()`; wrapper
    files may declare it round-wide) are real measurements of the wrong
    hardware — a CPU fallback round reading as a 99.9% regression, or a
    CPU round "recovering" to TPU reading as a win, would both poison
    the gate. Unannotated records (historic rounds) gate as before."""
    backend = rec.get("backend")
    return backend is None or str(backend).lower() == "tpu"


def diff_rounds(rounds: List[Tuple[str, dict]], key: str = "value",
                threshold: Optional[float] = None,
                lower_better: Tuple[str, ...] = ()) -> Tuple[list, list]:
    """(report_lines, regressions) across rounds (already ordered).
    A regression compares the LAST round's value against the most recent
    earlier round that carries the metric. A record born with
    ``"lower_better": true`` (MULTICHIP bubble/traffic synthesis) gates
    as lower-is-better without a CLI flag. Records whose ``backend``
    annotation is non-TPU are EXCLUDED from both the trajectory and the
    gate (reported as excluded, so the omission is visible). A record's
    ``model_version`` stamp (the serving bench carries the fitted
    model's content-addressed id, telemetry/lineage.py) rides the
    trajectory as ``label:value@version`` and annotates any regression
    whose two compared rounds measured DIFFERENT versions — a model
    swap and a perf regression must not read the same."""
    order: dict = {}   # metric -> [(label, value, version)] — insertion order
    born_lower: set = set()
    excluded: list = []
    for label, by_metric in rounds:
        for metric, rec in by_metric.items():
            v = rec.get(key)
            if not isinstance(v, (int, float)):
                continue
            if not _perf_backend(rec):
                excluded.append(f"{label} {metric} "
                                f"(backend={rec.get('backend')})")
                continue
            order.setdefault(metric, []).append(
                (label, float(v), rec.get("model_version")))
            if rec.get("lower_better"):
                born_lower.add(metric)
    lines: list = []
    regressions: list = []
    for metric, series in order.items():
        traj = " -> ".join(
            f"{label}:{value:g}" + (f"@{ver}" if ver else "")
            for label, value, ver in series)
        if len(series) < 2:
            lines.append(f"{metric} [{key}]: {traj}  (single round)")
            continue
        (_, prev, pver), (_, last, lver) = series[-2], series[-1]
        if last == prev:
            delta = 0.0   # unchanged is unchanged, even from a 0 baseline
        elif prev:
            delta = (last - prev) / abs(prev)
        else:
            delta = float("inf")
        lines.append(f"{metric} [{key}]: {traj}  last-vs-prev "
                     f"{delta:+.1%}")
        if threshold is not None:
            lb = metric in lower_better or metric in born_lower
            drop = delta if lb else -delta
            if drop > threshold:
                swap = (f", model_version {pver} -> {lver}"
                        if pver and lver and pver != lver else "")
                regressions.append(
                    f"{metric}: {prev:g} -> {last:g} "
                    f"({delta:+.1%}, threshold {threshold:.0%}"
                    f"{', lower-better' if lb else ''}{swap})")
    for note in excluded:
        lines.append(f"excluded from perf gates (non-TPU backend): {note}")
    return lines, regressions


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.telemetry.benchdiff",
        description="Per-metric deltas across bench round files; "
                    "nonzero exit on regression beyond --threshold.")
    parser.add_argument("files", nargs="+", help="BENCH_r*.json files")
    parser.add_argument("--key", default="value",
                        help="numeric field to diff (default: value)")
    parser.add_argument("--threshold", type=float, default=None,
                        help="fail when a metric regresses by more than "
                             "this fraction (e.g. 0.15 = 15%%)")
    parser.add_argument("--lower-better", action="append", default=[],
                        metavar="METRIC",
                        help="metric where a DROP is an improvement "
                             "(repeatable)")
    args = parser.parse_args(argv)
    rounds = []
    for path in args.files:
        try:
            sort_key, by_metric = load_round(path)
        except (OSError, ValueError) as e:
            # ValueError covers UnicodeDecodeError: a stray binary file
            # in the glob is "unreadable input" (exit 2), not a crash
            print(f"benchdiff: cannot read {path}: {e}", file=sys.stderr)
            return 2
        rounds.append((sort_key, path, by_metric))
    # wrapper `n` orders rounds when every file has one; natural
    # filename order otherwise (mixed keys are not comparable in py3)
    if all(isinstance(k, int) for k, _, _ in rounds):
        rounds.sort(key=lambda r: r[0])
    else:
        rounds.sort(key=lambda r: _natural_key(r[1]))
    labeled = [(f"r{k:02d}" if isinstance(k, int) else path, by)
               for k, path, by in rounds]
    lines, regressions = diff_rounds(
        labeled, key=args.key, threshold=args.threshold,
        lower_better=tuple(args.lower_better))
    for line in lines:
        print(line)
    if not lines:
        print("benchdiff: no numeric records found", file=sys.stderr)
        return 2
    if regressions:
        print(f"\nREGRESSIONS ({len(regressions)}):", file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
