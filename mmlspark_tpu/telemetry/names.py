"""Canonical metric / span / event / fault-site names — ONE place.

Every counter, gauge, histogram, wall-clock timing label, span, event,
and fault-injection site the framework records is declared here, with a
one-line description. `graftlint` (mmlspark_tpu/analysis) enforces the
contract in both directions: package call sites must use names declared
here (as the constants below — a raw literal that is not canonical is
flagged, with typo suggestions), and every declared name must appear in
the docs/observability.md name table.

Conventions:

- Names are dotted, `subsystem.signal[.detail]`, lowercase.
- Patterned names carry `{placeholder}` segments (e.g.
  ``train.step{step}``); the helpers below render them. Keep the
  placeholder text meaningful — it is the documentation.
- FAULT SITES ARE THE EXCEPTION to the use-the-constant rule: the
  literal at a `perturb("...")`/`fire("...")` call site is what the
  analyzer cross-references against chaos-test schedules
  (`fault-site-unknown` / `fault-site-untested`), so fire sites keep
  their strings inline and this registry validates them.
- This module is pure stdlib data: importable from every layer (and
  executed standalone by the analyzer) with zero dependency cost.

Metric-family names (counters/gauges/histograms/timings) share the
`MetricsRegistry.snapshot()` namespace — never reuse one name across two
of those kinds (`metric-kind-collision` enforces it).
"""
from __future__ import annotations

# --------------------------------------------------------------- counters
SERVING_SHED_REQUESTS = "serving.shed_requests"
SERVING_REQUEST_TOTAL = "serving.request.total"
SERVING_REQUEST_ERRORS = "serving.request.errors"
TELEMETRY_POLL_SAMPLES = "telemetry.poll.samples"
TELEMETRY_POLL_ERRORS = "telemetry.poll.errors"
SERVING_WORKER_RESTARTS = "serving.worker_restarts"
SERVING_REPLAYED_EPOCHS = "serving.replayed_epochs"
SERVING_SIGNAL_DRAINS = "serving.signal_drains"
SERVING_PLAN_HITS = "serving.plan.hits"
SERVING_PLAN_MISSES = "serving.plan.misses"
CHECKPOINT_SAVE_COUNT = "checkpoint.save.count"
CHECKPOINT_SAVE_BYTES = "checkpoint.save.bytes"
CHECKPOINT_CORRUPT_SKIPPED = "checkpoint.corrupt_skipped"
CHECKPOINT_DIGEST_MISMATCH = "checkpoint.digest_mismatch"
CHECKPOINT_WRITE_COALESCED = "checkpoint.write.coalesced"
CHECKPOINT_WRITE_ERRORS = "checkpoint.write.errors"
CHECKPOINT_FINALIZE_ERRORS = "checkpoint.finalize_errors"
TRAIN_RESUMES = "train.resumes"
TRAIN_STEP_RESTARTS = "train.step_restarts"
TRAIN_STEP_TIMEOUTS = "train.step_timeouts"
TRAIN_STEP_RETRIES = "train.step_retries"
TRAIN_PREEMPTED = "train.preempted"
TRAIN_PREEMPT_SIGNALS = "train.preempt_signals"
CLUSTER_REJOINS = "cluster.rejoins"
CLUSTER_HEARTBEAT_ERRORS = "cluster.heartbeat_errors"
CLUSTER_RENDEZVOUS_RETRIES = "cluster.rendezvous_retries"
CLUSTER_FENCE_REJECTS = "cluster.fence_rejects"
CLUSTER_HEARTBEAT_TMP_SWEPT = "cluster.heartbeat_tmp_swept"
ELASTIC_MANIFEST_COMMITS = "elastic.manifest.commits"
ELASTIC_MANIFEST_REJECTED = "elastic.manifest.rejected"
ELASTIC_SHRINKS = "elastic.shrinks"
ELASTIC_RESUMES = "elastic.resumes"
REGISTRY_REPORT_RETRIES = "registry.report_retries"
HTTP_RETRIES = "http.retries"
RETRY_RETRIES = "retry.retries"
DATA_WORKER_FAILURES = "data.worker_failures"
DATA_PREFETCH_ITEMS = "data.prefetch.items"
DATA_PREFETCH_STALLS = "data.prefetch.stalls"
DATA_PREFETCH_FULL = "data.prefetch.full"
PLAN_COMPILES = "plan.compiles"
PLAN_RECOMPILES = "plan.recompiles"
PLAN_COLLECTIVE_OPS = "plan.collective_ops"
PLAN_COLLECTIVE_BYTES = "plan.collective_bytes"
SERVING_PLAN_EVICTIONS = "serving.plan.evictions"
TELEMETRY_BUNDLE_DUMPS = "telemetry.bundle.dumps"
TELEMETRY_BUNDLE_SUPPRESSED = "telemetry.bundle.suppressed"
TELEMETRY_PROFILE_CAPTURES = "telemetry.profile.captures"
TELEMETRY_PROFILE_SUPPRESSED = "telemetry.profile.suppressed"
TELEMETRY_PROFILE_STAMP_ERRORS = "telemetry.profile.stamp_errors"
TELEMETRY_WATCH_TRIPS = "telemetry.watch.trips"
QUALITY_LABELS_JOINED = "quality.labels.joined"
QUALITY_LABELS_LATE = "quality.labels.late"
QUALITY_LABELS_DUP = "quality.labels.dup"
QUALITY_LABELS_DROPPED = "quality.labels.dropped"
QUALITY_JOIN_SUBSCRIBER_ERRORS = "quality.join.subscriber_errors"
QUALITY_SKETCH_ROWS = "quality.sketch.rows"
ONLINE_FEED_PAIRS = "online.feed.pairs"
ONLINE_FEED_DROPPED = "online.feed.dropped"
ONLINE_LEARNER_UPDATES = "online.learner.updates"
ONLINE_TRIPS = "online.trips"
ONLINE_REFITS = "online.refits"
ONLINE_REFIT_RETRIES = "online.refit_retries"
ONLINE_PROMOTIONS = "online.promotions"
ONLINE_ROLLBACKS = "online.rollbacks"
SERVING_MODEL_SWAPS = "serving.model.swaps"
SERVING_MODEL_SWAP_ERRORS = "serving.model.swap_errors"
REGISTRY_EVICTIONS = "registry.evictions"
CONTROL_ROLLOUT_STEPS = "control.rollout.steps"
CONTROL_ROLLOUT_PROMOTIONS = "control.rollout.promotions"
CONTROL_ROLLOUT_ROLLBACKS = "control.rollout.rollbacks"
CONTROL_ROLLOUT_ROLLBACK_RETRIES = "control.rollout.rollback_retries"
CONTROL_ROLLOUT_POLL_ERRORS = "control.rollout.poll_errors"
CONTROL_ADMISSION_SHED = "control.admission.shed"
CONTROL_ROUTER_UPDATES = "control.router.updates"
CONTROL_SCALER_SPAWNS = "control.scaler.spawns"
CONTROL_SCALER_DRAINS = "control.scaler.drains"
WORKLOADS_IFOREST_TREES = "workloads.iforest.trees"
WORKLOADS_SAR_RECOMMEND_ROWS = "workloads.sar.recommend.rows"
WORKLOADS_SAR_UNKNOWN_USERS = "workloads.sar.unknown_users"

COUNTERS = {
    SERVING_SHED_REQUESTS: "requests answered 503 (drain or max_queue "
                           "load shedding)",
    SERVING_REQUEST_TOTAL: "requests accepted at ingress (exposition "
                           "self-scrapes excluded) — SLO denominators",
    SERVING_REQUEST_ERRORS: "requests answered 5xx (shed, timeout, model "
                            "failure) — SLO error-budget numerators",
    TELEMETRY_POLL_SAMPLES: "fleet snapshots captured by TelemetryPoller",
    TELEMETRY_POLL_ERRORS: "TelemetryPoller scrape rounds that failed "
                           "(absorbed; last good sample stands)",
    SERVING_WORKER_RESTARTS: "partition worker threads restarted by the "
                             "watchdog",
    SERVING_REPLAYED_EPOCHS: "uncommitted epochs replayed after a worker "
                             "death/failure",
    SERVING_SIGNAL_DRAINS: "SIGTERM/SIGINT graceful drains taken",
    SERVING_PLAN_HITS: "compiled-plan cache hits (fingerprint, bucket)",
    SERVING_PLAN_MISSES: "compiled-plan cache misses (one compile each)",
    CHECKPOINT_SAVE_COUNT: "checkpoints written",
    CHECKPOINT_SAVE_BYTES: "bytes written across checkpoint payloads",
    CHECKPOINT_CORRUPT_SKIPPED: "truncated/unreadable checkpoint steps "
                                "skipped on restore",
    CHECKPOINT_DIGEST_MISMATCH: "checkpoint steps failing SHA-256 verify "
                                "on restore",
    CHECKPOINT_WRITE_COALESCED: "async snapshots dropped latest-wins "
                                "under backpressure",
    CHECKPOINT_WRITE_ERRORS: "async checkpoint writes that failed "
                             "(absorbed)",
    CHECKPOINT_FINALIZE_ERRORS: "final-checkpoint failures during "
                                "supervisor finalize",
    TRAIN_RESUMES: "supervisor runs resumed from a checkpoint",
    TRAIN_STEP_RESTARTS: "step-loop restarts from the in-memory snapshot",
    TRAIN_STEP_TIMEOUTS: "steps killed by the step_timeout watchdog",
    TRAIN_STEP_RETRIES: "step retry attempts under the restart "
                        "RetryPolicy",
    TRAIN_PREEMPTED: "runs ended by preemption (final checkpoint taken)",
    TRAIN_PREEMPT_SIGNALS: "SIGTERM/SIGINT deliveries observed mid-run",
    CLUSTER_REJOINS: "processes that found their own prior heartbeat at "
                     "startup",
    CLUSTER_HEARTBEAT_ERRORS: "heartbeat writes that failed (counted, "
                              "never fatal)",
    CLUSTER_RENDEZVOUS_RETRIES: "jax.distributed rendezvous connection "
                                "retries",
    CLUSTER_FENCE_REJECTS: "heartbeat writes rejected by the epoch fence "
                           "(a zombie host beating after its death "
                           "verdict; the row is never written)",
    CLUSTER_HEARTBEAT_TMP_SWEPT: "stale heartbeat .tmp files (a crash "
                                 "between tmp-write and os.replace) swept "
                                 "at Heartbeat startup",
    ELASTIC_MANIFEST_COMMITS: "fleet checkpoint manifests committed by "
                              "the leader (every member shard landed and "
                              "digest-recorded)",
    ELASTIC_MANIFEST_REJECTED: "fleet manifests refused on restore "
                               "(torn JSON, missing member shard, or "
                               "member digest mismatch) — restore falls "
                               "back to the last fully-committed step",
    ELASTIC_SHRINKS: "shrink plans derived after a death verdict "
                     "(survivor set + chunk restage computed)",
    ELASTIC_RESUMES: "shrink-resumes taken from a committed fleet "
                     "manifest",
    REGISTRY_REPORT_RETRIES: "worker->registry registration retries",
    HTTP_RETRIES: "HTTP handler retry attempts (io/http.py)",
    RETRY_RETRIES: "generic utils.retry attempts",
    DATA_WORKER_FAILURES: "ingest pool chunk failures (first failing "
                          "chunk raises)",
    DATA_PREFETCH_ITEMS: "batches fed through DevicePrefetcher",
    DATA_PREFETCH_STALLS: "consumer arrived at an empty prefetch queue",
    DATA_PREFETCH_FULL: "feeder found the prefetch queue full (device is "
                        "the bottleneck)",
    PLAN_COMPILES: "plan builds / AOT jit compiles recorded "
                   "(telemetry.perf compile log)",
    PLAN_RECOMPILES: "a (fingerprint, shape bucket) compiled AGAIN — "
                     "steady-state serving pins this to zero",
    PLAN_COLLECTIVE_OPS: "collective instructions (all-reduce, "
                         "collective-permute, ...) in recorded executables",
    PLAN_COLLECTIVE_BYTES: "per-device collective payload bytes in "
                           "recorded executables (COMM_TRAFFIC account)",
    SERVING_PLAN_EVICTIONS: "compiled plans evicted (LRU) from the "
                            "bounded plan cache",
    TELEMETRY_BUNDLE_DUMPS: "flight-recorder debug bundles written",
    TELEMETRY_BUNDLE_SUPPRESSED: "flight-recorder triggers suppressed by "
                                 "the rate limit",
    TELEMETRY_PROFILE_CAPTURES: "device-profile captures written "
                                "(ProfileSession)",
    TELEMETRY_PROFILE_SUPPRESSED: "profile triggers suppressed by the "
                                  "capture rate limit",
    TELEMETRY_PROFILE_STAMP_ERRORS: "trace_context.json stamps that "
                                    "failed (capture kept, stamp lost)",
    TELEMETRY_WATCH_TRIPS: "telemetry watcher rule trip TRANSITIONS "
                           "(threshold or median-shift)",
    QUALITY_LABELS_JOINED: "delayed labels joined to their served "
                           "prediction (streaming evaluation pairs)",
    QUALITY_LABELS_LATE: "out-of-order labels that arrived BEFORE their "
                         "prediction and joined late",
    QUALITY_LABELS_DUP: "duplicate labels for an already-joined request "
                        "id (counted, not re-joined)",
    QUALITY_LABELS_DROPPED: "labels lost to the join: prediction aged "
                            "out of the bounded window, parked-label "
                            "eviction, or injected label loss",
    QUALITY_JOIN_SUBSCRIBER_ERRORS: "on_join subscriber callbacks that "
                                    "raised (absorbed; the join itself "
                                    "is never undone)",
    QUALITY_SKETCH_ROWS: "served rows folded into the live quality "
                         "sketches (head-sampled by request id)",
    ONLINE_FEED_PAIRS: "joined (features, label) pairs buffered by the "
                       "LabelFeed for incremental refits",
    ONLINE_FEED_DROPPED: "joined pairs the LabelFeed lost: features "
                         "evicted before the label joined, or the "
                         "bounded pair buffer overflowed",
    ONLINE_LEARNER_UPDATES: "compiled minibatch updates applied by the "
                            "OnlineLearner (one per padded (rows, k) "
                            "bucket execution)",
    ONLINE_TRIPS: "continuous-learner triggers (drift trip or quality "
                  "floor burn) that started a refit cycle",
    ONLINE_REFITS: "incremental refits that completed and produced a "
                   "candidate ModelVersion",
    ONLINE_REFIT_RETRIES: "refit attempts retried under the continuous "
                          "learner's RetryPolicy (each retry rewinds to "
                          "the pre-refit snapshot first)",
    ONLINE_PROMOTIONS: "online candidates promoted by the rollout gate",
    ONLINE_ROLLBACKS: "online candidates rolled back by the rollout "
                      "gate (learner state rewound to the pre-refit "
                      "snapshot)",
    SERVING_MODEL_SWAPS: "install_model hot-swaps committed (the old "
                         "version's plans drain, never invalidate)",
    SERVING_MODEL_SWAP_ERRORS: "install_model swaps that failed and "
                               "rolled back to the incumbent handle",
    REGISTRY_EVICTIONS: "registry entries evicted because no "
                        "re-registration heartbeat landed within the TTL",
    CONTROL_ROLLOUT_STEPS: "candidate traffic-step installs performed by "
                           "the rollout driver (one per staged fraction)",
    CONTROL_ROLLOUT_PROMOTIONS: "rollouts auto-promoted after a clean "
                                "soak window",
    CONTROL_ROLLOUT_ROLLBACKS: "rollouts auto-rolled-back to the "
                               "incumbent (burn or watch trip)",
    CONTROL_ROLLOUT_ROLLBACK_RETRIES: "rollback install_model attempts "
                                      "retried under the driver's "
                                      "RetryPolicy",
    CONTROL_ROLLOUT_POLL_ERRORS: "rollout-driver fleet scrapes that "
                                 "failed (absorbed; the round is skipped)",
    CONTROL_ADMISSION_SHED: "requests shed 503+Retry-After by burn-aware "
                            "admission (error budget burning, queue "
                            "non-empty)",
    CONTROL_ROUTER_UPDATES: "weighted-router weight table refreshes from "
                            "fleet scrapes",
    CONTROL_SCALER_SPAWNS: "spawn hooks fired by the occupancy-driven "
                           "fleet scaler",
    CONTROL_SCALER_DRAINS: "drain hooks fired by the occupancy-driven "
                           "fleet scaler",
    WORKLOADS_IFOREST_TREES: "isolation trees grown (one supervisor step "
                             "each — the resumable fit cursor's rate)",
    WORKLOADS_SAR_RECOMMEND_ROWS: "user rows answered by the compiled "
                                  "SAR recommend plan (served top-k "
                                  "batches, after bucket-pad trim)",
    WORKLOADS_SAR_UNKNOWN_USERS: "recommend requests for user ids "
                                 "outside the fitted range (answered "
                                 "items=-1/ratings=NaN, the cold-start "
                                 "convention)",
    "data.pool.{mode}_maps": "WorkerPool.map_rows calls per backend "
                             "(process/thread)",
    "gbdt.hist.route.{route}": "histogram kernel-route selections "
                               "(direct/joint/planes/xla), recorded at "
                               "trace time — one per compiled (m, B) "
                               "instantiation",
    "{breaker}.trips": "circuit-breaker trips, one counter per breaker "
                       "name",
}

# ----------------------------------------------------------------- gauges
ANALYSIS_SEMANTIC_CONTRACTS = "analysis.semantic.contracts"
ANALYSIS_SEMANTIC_FINDINGS = "analysis.semantic.findings"
GBDT_HIST_PLAN_BYTES = "gbdt.hist.plan.bytes"
SERVING_QUEUE_DEPTH = "serving.queue_depth"
SERVING_BATCH_OCCUPANCY = "serving.batch.occupancy"
CHECKPOINT_WRITE_PENDING = "checkpoint.write.pending"
TRAIN_RESUME_STEP = "train.resume_step"
CLUSTER_RESUME_EPOCH = "cluster.resume_epoch"
DEVICE_MEM_BYTES_IN_USE = "device.mem.bytes_in_use"
DEVICE_MEM_PEAK_BYTES = "device.mem.peak_bytes"
HOST_RSS_BYTES = "host.rss_bytes"
TRAIN_GOODPUT = "train.goodput"
TRAIN_MFU = "train.mfu"
TRAIN_LOST_SECONDS = "train.lost_seconds"
TRAIN_STRAGGLERS = "train.stragglers"
TELEMETRY_WATCH_TRIPPED = "telemetry.watch.tripped"
QUALITY_DRIFT_MAX = "quality.drift.max"
ONLINE_BUFFER_PAIRS = "online.buffer.pairs"
SERVING_MODEL_VERSION_INFO = "serving.model.version_info"
CANARY_P99_RATIO = "canary.p99.ratio"
CANARY_ERROR_BURN = "canary.error_burn"
CANARY_DRIFT_DELTA = "canary.drift.delta"
CONTROL_ROLLOUT_FRACTION = "control.rollout.fraction"
DATA_OOCORE_RESIDENT_BYTES = "data.oocore.resident_bytes"
DATA_OOCORE_CURSOR = "data.oocore.cursor"
CLUSTER_HOSTS_LIVE = "cluster.hosts.live"
CLUSTER_HOSTS_DEAD = "cluster.hosts.dead"
WORKLOADS_IFOREST_THRESHOLD = "workloads.iforest.threshold"
WORKLOADS_SAR_CATALOG_ITEMS = "workloads.sar.catalog.items"

GAUGES = {
    ANALYSIS_SEMANTIC_CONTRACTS: "hot-path contracts analyzed by the last "
                                 "semantic-tier run",
    ANALYSIS_SEMANTIC_FINDINGS: "findings (incl. contract-import errors) "
                                "from the last semantic-tier run",
    GBDT_HIST_PLAN_BYTES: "resident level-invariant one-hot plane bytes "
                          "built for the current fit "
                          "(MMLSPARK_TPU_HIST=planes)",
    SERVING_QUEUE_DEPTH: "partition queue depth at last enqueue",
    SERVING_BATCH_OCCUPANCY: "live-rows / max_batch of the last "
                             "dispatched batch",
    CHECKPOINT_WRITE_PENDING: "async checkpoint snapshots queued",
    TRAIN_RESUME_STEP: "step the supervisor resumed from",
    CLUSTER_RESUME_EPOCH: "epoch found in this process's prior heartbeat",
    DEVICE_MEM_BYTES_IN_USE: "bytes in use summed over local devices "
                             "(absent where memory_stats() is)",
    DEVICE_MEM_PEAK_BYTES: "peak bytes in use summed over local devices",
    HOST_RSS_BYTES: "host process resident set size (bytes)",
    TRAIN_GOODPUT: "productive fraction of training wall clock "
                   "(1 - (data-wait + checkpoint-stall + lost) / wall)",
    TRAIN_MFU: "model-flops utilization: flops_per_step * steps / "
               "(wall * peak_flops); absent when either flops side is "
               "unknown",
    TRAIN_LOST_SECONDS: "cumulative lost training seconds (restart/replay "
                        "rewinds, injected stalls, failed step attempts)",
    TRAIN_STRAGGLERS: "hosts currently flagged by straggler detection "
                      "(windowed step p50 beyond threshold x fleet median)",
    TELEMETRY_WATCH_TRIPPED: "telemetry watcher rules currently in the "
                             "tripped state",
    QUALITY_DRIFT_MAX: "worst per-column PSI between the frozen "
                       "reference profile and the live serving sketches "
                       "(the quality SLO's drift-ceiling input)",
    ONLINE_BUFFER_PAIRS: "joined pairs currently buffered in the "
                         "LabelFeed (drains on each refit)",
    SERVING_MODEL_VERSION_INFO: "number of model versions currently "
                                "tracked (incumbent + candidate); the "
                                "served version ids ride /versions",
    CANARY_P99_RATIO: "candidate windowed request p99 / incumbent frozen "
                      "p99 (absent until a swap installs a candidate)",
    CANARY_ERROR_BURN: "candidate windowed error rate / the canary error "
                       "budget (absent until a swap installs a candidate)",
    CANARY_DRIFT_DELTA: "candidate live quality.drift.max minus the "
                        "incumbent's frozen drift at swap time",
    CONTROL_ROLLOUT_FRACTION: "traffic fraction the rollout driver "
                              "currently targets for the candidate "
                              "(0 after rollback, 1 at/after promote)",
    DATA_OOCORE_RESIDENT_BYTES: "raw-input bytes the out-of-core stager "
                                "may hold host-resident at once (the "
                                "bounded in-flight window, not the full "
                                "dataset)",
    DATA_OOCORE_CURSOR: "chunks durably binned into the out-of-core "
                        "spill cache so far (the resume cursor a killed "
                        "staging pass restarts from)",
    CLUSTER_HOSTS_LIVE: "hosts currently holding a live lease (beat "
                        "observed within lease_timeout_s of the "
                        "observer's monotonic clock)",
    CLUSTER_HOSTS_DEAD: "hosts declared dead by lease expiry (fenced "
                        "out; stays counted until a fresh observer "
                        "starts)",
    WORKLOADS_IFOREST_THRESHOLD: "contamination score threshold of the "
                                 "last fitted isolation forest (2.0 = "
                                 "labeling disabled)",
    WORKLOADS_SAR_CATALOG_ITEMS: "item-catalog width of the last fitted "
                                 "SAR serving model (the sharded matmul's "
                                 "contraction axis before mesh padding)",
    "control.router.weight.{target}": "weighted-router relative weight "
                                      "per target (host:port), 1..100 — "
                                      "scaled from scraped queue depth "
                                      "and windowed p99",
    "quality.drift.{col}": "per-column PSI drift, reference vs live "
                           "sketch counts over the shared bucket grid "
                           "(refreshed on every exposition scrape)",
    "quality.eval.{metric}": "current streaming-evaluation metric value "
                             "(accuracy/precision/recall or rmse/mae) "
                             "from the delayed-label join",
    "device{ordinal}.mem.bytes_in_use": "per-device bytes in use "
                                        "(memory_stats)",
    "device{ordinal}.mem.peak_bytes": "per-device peak bytes in use "
                                      "(memory_stats)",
    "op.{region}.hbm_util": "per-region achieved / peak HBM bytes/s "
                            "(RooflineLedger; absent when either side "
                            "is unknown)",
    "op.{region}.flops_util": "per-region achieved / peak FLOP/s "
                              "(RooflineLedger; absent when either side "
                              "is unknown)",
}

# ------------------------------------------------------------- histograms
SERVING_REQUEST_QUEUE = "serving.request.queue"
SERVING_REQUEST_TRANSFORM = "serving.request.transform"
SERVING_REQUEST_REPLY = "serving.request.reply"
SERVING_REQUEST_E2E = "serving.request.e2e"
CHECKPOINT_SUBMIT = "checkpoint.submit"
CHECKPOINT_SNAPSHOT = "checkpoint.snapshot"
CHECKPOINT_WRITE = "checkpoint.write"
PLAN_COMPILE = "plan.compile"
TRAIN_STEP_WALL = "train.step.wall"

HISTOGRAMS = {
    PLAN_COMPILE: "plan build / AOT jit compile duration (ms)",
    TRAIN_STEP_WALL: "one training step's wall clock (ms) — the "
                     "straggler detector's windowed p50 source",
    "train.step.{phase}": "per-step phase time (ms): data_wait / host / "
                          "device / checkpoint / lost (StepClock)",
    SERVING_REQUEST_QUEUE: "ingress enqueue -> worker drain, per request "
                           "(ms)",
    SERVING_REQUEST_TRANSFORM: "transform duration per batch (ms)",
    SERVING_REQUEST_REPLY: "reply routing duration per batch (ms)",
    SERVING_REQUEST_E2E: "enqueue -> response routed, per request (ms)",
    CHECKPOINT_SUBMIT: "step-thread time to hand a snapshot to the "
                       "async writer (ms)",
    CHECKPOINT_SNAPSHOT: "snapshot_fn duration on the step thread (ms)",
    CHECKPOINT_WRITE: "checkpoint write duration, sync and async (ms)",
}

# ------------------------------------------------- wall-clock timing labels
DATA_PREFETCH_PUT = "data.prefetch.put"
DATA_BIN_CHUNK = "data.bin_chunk"
DATA_FIT_BINS = "data.fit_bins"
DATA_APPLY_BINS = "data.apply_bins"
DATA_STAGE_BINNED = "data.stage_binned"
DATA_TABLE_TRANSFORM = "data.table_transform"

TIMINGS = {
    DATA_PREFETCH_PUT: "feeder time spent in device_put",
    DATA_BIN_CHUNK: "per-chunk binning transform wall clock",
    DATA_FIT_BINS: "quantile bin fit wall clock",
    DATA_APPLY_BINS: "parallel bin application wall clock",
    DATA_STAGE_BINNED: "stage_binned end-to-end wall clock",
    DATA_TABLE_TRANSFORM: "ParallelTransform table pass wall clock",
    "data.pool.map[{mode}]": "WorkerPool.map_rows wall clock per backend",
}

# ------------------------------------------------------------------ spans
SERVING_REQUEST_SPAN = "serving.request"
SERVING_PARTITION_TRANSFORM_SPAN = "serving.partition.transform"
SERVING_PLAN_RUN_SPAN = "serving.plan.run"
PLAN_COMPILE_SPAN = "plan.compile"
TRAIN_STEP_SPAN = "train.step"
CHECKPOINT_WRITE_SPAN = "checkpoint.write"
DATA_PREFETCH_SPAN = "data.prefetch"
GBDT_FIT_SPAN = "gbdt.fit"
GBDT_ITERATION_SPAN = "gbdt.iteration"
GBDT_CHUNK_SPAN = "gbdt.chunk"
LM_RUN_STREAM_SPAN = "lm.run_stream"
DEVICE_PROFILE_SPAN = "device.profile"

SPANS = {
    PLAN_COMPILE_SPAN: "one plan build / AOT compile (fingerprint, "
                       "bucket attrs; same name as the histogram, like "
                       "checkpoint.write)",
    SERVING_REQUEST_SPAN: "ingress root span per request (== request id)",
    SERVING_PARTITION_TRANSFORM_SPAN: "worker-hop child span per sampled "
                                      "request",
    SERVING_PLAN_RUN_SPAN: "compiled-plan execution per batch",
    TRAIN_STEP_SPAN: "one supervised training step (covers the fault "
                     "site)",
    CHECKPOINT_WRITE_SPAN: "one checkpoint write attempt (sync/async, "
                           "ok/error)",
    DATA_PREFETCH_SPAN: "DevicePrefetcher lifecycle (depth, items, "
                        "stalls)",
    GBDT_FIT_SPAN: "whole fit_booster call",
    GBDT_ITERATION_SPAN: "one boosting iteration (host loop)",
    GBDT_CHUNK_SPAN: "one fused boosting chunk (scan path)",
    LM_RUN_STREAM_SPAN: "ShardedLMTrainer.run_stream lifecycle",
    DEVICE_PROFILE_SPAN: "utils.tracing.trace device-profile capture",
    "stage.{stage}.{action}": "Timer-wrapped stage fit/transform "
                              "(telemetry=True)",
}

# ----------------------------------------------------------------- events
FAULT_INJECTED_EVENT = "fault.injected"
TRAIN_RESUME_EVENT = "train.resume"
TRAIN_RESTART_EVENT = "train.restart"
TRAIN_PREEMPTED_EVENT = "train.preempted"
TRAIN_STRAGGLER_EVENT = "train.straggler"
TRAIN_CHUNK_REASSIGN_EVENT = "train.chunk.reassign"
TRAIN_HOST_DEAD_EVENT = "train.host.dead"
ELASTIC_PLAN_EVENT = "elastic.plan"
ELASTIC_RESUME_EVENT = "elastic.resume"
TELEMETRY_BUNDLE_EVENT = "telemetry.bundle"
TELEMETRY_PROFILE_EVENT = "telemetry.profile"
TELEMETRY_WATCH_TRIP_EVENT = "telemetry.watch.trip"
SERVING_MODEL_SWAP_EVENT = "serving.model.swap"
CONTROL_ROLLOUT_DEPLOY_EVENT = "control.rollout.deploy"
CONTROL_ROLLOUT_STEP_EVENT = "control.rollout.step"
CONTROL_ROLLOUT_BURN_EVENT = "control.rollout.burn"
CONTROL_ROLLOUT_PROMOTE_EVENT = "control.rollout.promote"
CONTROL_ROLLOUT_ROLLBACK_EVENT = "control.rollout.rollback"
CONTROL_ROLLOUT_RECOVERED_EVENT = "control.rollout.recovered"
ONLINE_TRIP_EVENT = "online.trip"
ONLINE_REFIT_EVENT = "online.refit"
ONLINE_DEPLOY_EVENT = "online.deploy"
ONLINE_PROMOTE_EVENT = "online.promote"
ONLINE_ROLLBACK_EVENT = "online.rollback"

EVENTS = {
    FAULT_INJECTED_EVENT: "one FaultInjector firing (site, index, kind)",
    TRAIN_STRAGGLER_EVENT: "a host's windowed step p50 deviated beyond "
                           "the straggler threshold (host, p50, fleet "
                           "median attrs)",
    TRAIN_HOST_DEAD_EVENT: "a host's lease aged past lease_timeout_s of "
                           "observer-local clock — death verdict "
                           "TRANSITION (host, age_s attrs); the fence "
                           "bump rides the same transition",
    ELASTIC_PLAN_EVENT: "survivor-side shrink plan derived after a death "
                        "verdict (dead, survivors, restaged-chunk "
                        "attrs) — ordered after train.host.dead",
    ELASTIC_RESUME_EVENT: "training resumed from the committed fleet "
                          "manifest on the shrunk host set (step, "
                          "survivors attrs) — ordered after elastic.plan",
    TRAIN_CHUNK_REASSIGN_EVENT: "ChunkPlanner drained a flagged host's "
                                "pending chunks to healthy hosts "
                                "(from_host, to_hosts, chunks attrs) — "
                                "ordered after the train.straggler flag "
                                "that triggered it",
    TELEMETRY_BUNDLE_EVENT: "one flight-recorder bundle written (reason, "
                            "path)",
    TELEMETRY_PROFILE_EVENT: "one device-profile capture written "
                             "(reason, path, parsed op count)",
    TELEMETRY_WATCH_TRIP_EVENT: "a watched telemetry series breached its "
                                "rule (key, kind, value, bound/baseline "
                                "attrs)",
    TRAIN_RESUME_EVENT: "supervisor resumed from a checkpoint",
    TRAIN_RESTART_EVENT: "supervisor restarted the step loop from the "
                         "in-memory snapshot",
    TRAIN_PREEMPTED_EVENT: "supervisor took the preemption exit",
    SERVING_MODEL_SWAP_EVENT: "one committed install_model hot-swap "
                              "(old/new version ids, plan-cache size "
                              "attrs)",
    CONTROL_ROLLOUT_DEPLOY_EVENT: "rollout started: candidate installed "
                                  "on the first traffic step (candidate/"
                                  "incumbent version, fraction attrs)",
    CONTROL_ROLLOUT_STEP_EVENT: "rollout advanced one traffic step "
                                "(fraction, workers attrs)",
    CONTROL_ROLLOUT_BURN_EVENT: "rollout observed a burn or watch trip — "
                                "the rollback trigger (reason attr)",
    CONTROL_ROLLOUT_PROMOTE_EVENT: "rollout auto-promoted the candidate "
                                   "after its soak window",
    CONTROL_ROLLOUT_ROLLBACK_EVENT: "rollout re-installed the incumbent "
                                    "fleet-wide (reason, workers attrs)",
    CONTROL_ROLLOUT_RECOVERED_EVENT: "post-rollback fleet SLO verdict "
                                     "returned to ok (ok attr False when "
                                     "the wait timed out)",
    ONLINE_TRIP_EVENT: "continuous learner triggered a refit cycle "
                       "(reason drift/floor-burn, buffered-pairs attrs) "
                       "— always journaled before online.refit",
    ONLINE_REFIT_EVENT: "incremental refit completed: candidate "
                        "ModelVersion + lineage (version, updates, "
                        "examples, loss attrs)",
    ONLINE_DEPLOY_EVENT: "candidate handed to the rollout gate "
                         "(version attr) — journaled after online.refit, "
                         "before the rollout's own deploy event",
    ONLINE_PROMOTE_EVENT: "rollout gate promoted the online candidate "
                          "(version attr); terminal event of a healthy "
                          "cycle",
    ONLINE_ROLLBACK_EVENT: "rollout gate rejected the online candidate — "
                           "incumbent restored, learner rewound to the "
                           "pre-refit snapshot (version attr)",
    "registry.{action}": "registry HTTP hops (register/unregister) under "
                         "the caller's propagated trace",
}

# ------------------------------------------------------------- fault sites
# Fire sites keep their literals inline (see module docstring); this is
# the canonical list the analyzer validates both code and chaos tests
# against. Patterned sites carry the per-call index in the name.
FAULT_SITES = {
    "serving.ingress": "selector-transport ingress, fired per parsed "
                       "request (kind `reset` drops the socket)",
    "serving.worker": "partition worker between batch read and commit",
    "train.step{step}": "supervisor step k, fired before the step fn",
    "train.ckpt.write": "checkpoint write path (sync and async)",
    "train.ckpt.read": "checkpoint restore path",
    "cluster.heartbeat": "Heartbeat.beat() before the atomic write",
    "cluster.lease.expire": "HostLeases.check(), fired once per "
                            "(round, host) in sorted host order (kind "
                            "`expire` forces a false-positive death "
                            "verdict on that host — fencing then "
                            "rejects its next beat exactly once; kind "
                            "`error` skips the whole check round)",
    "elastic.commit": "FleetCheckpoint.commit between the manifest "
                      "tmp-write and its os.replace (kind `crash` "
                      "models the leader dying mid-commit — no "
                      "manifest lands, the next leader re-commits; a "
                      "torn manifest is never restored)",
    "data.worker.chunk{index}": "ingest pool, fired before chunk i's "
                                "transform",
    "data.oocore.stage{index}": "out-of-core stager, fired before chunk "
                                "i's binned rows are written to the "
                                "spill cache (kind `error` aborts "
                                "staging mid-dataset — the durable "
                                "cursor resumes from the last flushed "
                                "chunk; `delay` stretches staging so a "
                                "SIGTERM can land mid-epoch)",
    "data.planner.reassign": "ChunkPlanner.reassign, fired before the "
                             "pending-chunk migration commits (kind "
                             "`error` skips this reassignment round — "
                             "the flagged host keeps its chunks until "
                             "the next straggler check; `delay` "
                             "stretches the actuation)",
    "fuzz.http": "corrupt_bytes stream for the malformed-HTTP fuzz "
                 "corpus",
    "checkpoint": "corrupt_file default site (checkpoint corruption "
                  "tests)",
    "quality.label": "StreamingEvaluator.record_label, fired per "
                     "arriving label (kind `drop` loses the label "
                     "before the join — counted quality.labels.dropped)",
    "serving.swap": "ServingTransform.install_model, fired after the new "
                    "handle is built but before it commits (a raise "
                    "rolls back to the incumbent — counted "
                    "serving.model.swap_errors)",
    "control.rollout.poll": "RolloutDriver fleet scrape, fired before "
                            "each poll round (kind `error` counts "
                            "control.rollout.poll_errors and skips the "
                            "round; `delay` stretches the poll)",
    "online.refit": "ContinuousLearner refit, fired after the minibatch "
                    "updates but before the candidate model is built (a "
                    "raise rewinds the learner to the pre-refit snapshot "
                    "and retries — counted online.refit_retries; the "
                    "incumbent keeps serving throughout)",
    "workloads.sar.refit": "SARServing._fit, fired after the similarity "
                           "build but before the model assembles (a "
                           "raise aborts the candidate fit — a serving "
                           "incumbent is untouched because install_model "
                           "only ever sees a whole fitted model)",
}

# ------------------------------------------- benchdiff record names
# Not registry metrics (nothing inc()s or gauges them): these are the
# canonical names of JSON records bench.py emits and benchdiff gates.
# They live here so the bench writer and the gate assertions share one
# spelling (docs/observability.md "MULTICHIP rounds gate like bench
# rounds" describes the record shape benchdiff gates).
COMM_GBDT_VOTE_OPS = "comm.gbdt.vote.ops"
COMM_GBDT_VOTE_BYTES = "comm.gbdt.vote.bytes"


# ------------------------------------------------- patterned-name helpers
def data_pool_maps(mode: str) -> str:
    """data.pool.{mode}_maps — per-backend WorkerPool map counter."""
    return f"data.pool.{mode}_maps"


def data_pool_map_timing(mode: str) -> str:
    """data.pool.map[{mode}] — per-backend map wall-clock label."""
    return f"data.pool.map[{mode}]"


def breaker_trips(breaker: str) -> str:
    """{breaker}.trips — per-breaker trip counter."""
    return f"{breaker}.trips"


def stage_span(stage: str, action: str) -> str:
    """stage.{stage}.{action} — Timer span label."""
    return f"stage.{stage}.{action}"


def device_mem_in_use(ordinal: int) -> str:
    """device{ordinal}.mem.bytes_in_use — per-device in-use gauge."""
    return f"device{ordinal}.mem.bytes_in_use"


def device_mem_peak(ordinal: int) -> str:
    """device{ordinal}.mem.peak_bytes — per-device peak gauge."""
    return f"device{ordinal}.mem.peak_bytes"


def train_step_phase(phase: str) -> str:
    """train.step.{phase} — per-phase step-time histogram."""
    return f"train.step.{phase}"


def gbdt_hist_route(route: str) -> str:
    """gbdt.hist.route.{route} — per-route kernel-selection counter."""
    return f"gbdt.hist.route.{route}"


def op_hbm_util(region: str) -> str:
    """op.{region}.hbm_util — per-region roofline HBM utilization."""
    return f"op.{region}.hbm_util"


def op_flops_util(region: str) -> str:
    """op.{region}.flops_util — per-region roofline FLOPs utilization."""
    return f"op.{region}.flops_util"


def quality_drift(col: str) -> str:
    """quality.drift.{col} — per-column PSI drift gauge."""
    return f"quality.drift.{col}"


def quality_eval(metric: str) -> str:
    """quality.eval.{metric} — streaming-evaluation metric gauge."""
    return f"quality.eval.{metric}"


def control_router_weight(target: str) -> str:
    """control.router.weight.{target} — per-target router weight gauge."""
    return f"control.router.weight.{target}"
