"""Performance observability: compile/cost telemetry, resource gauges,
and the burn-triggered flight recorder.

PR 7's windowed/SLO tier can say *that* a latency objective is burning;
nothing in the tree could say *why*: XLA compiles, executable cost and
memory footprints, and device/host memory pressure were uninstrumented,
and the moment of distress left no durable artifact. This module closes
those gaps (docs/observability.md "Performance observability"):

- **Compile log** (`CompileLog` / `record_plan_compile`): every serving
  plan build and AOT jit compile records a `plan.compile` span +
  histogram and per-(pipeline fingerprint, shape bucket) compile
  counts/seconds in a bounded LRU map. A key compiled MORE than once is
  a *recompile* (`plan.recompiles`) — the signal the shape-bucket design
  exists to pin at zero on the steady-state serving path, and the plan
  cache's LRU eviction pressure made visible. This per-key compile data
  is the training signal ROADMAP item 4's learned cost model needs
  (*A Learned Performance Model for TPUs*, PAPERS.md).
- **Executable analysis** (`executable_analysis` /
  `compile_with_analysis`): captures `cost_analysis()` (flops, bytes
  accessed) and `memory_analysis()` (generated-code/argument/output/temp
  bytes) from a compiled XLA executable, degrading field-by-field where
  a backend omits them (the CPU backend reports cost but not
  `memory_stats`; TPU reports both).
- **Collective traffic** (`collective_traffic` and the `collectives`
  field of `executable_analysis`): per-executable collective ops/bytes
  (all-reduce, all-gather, reduce-scatter, collective-permute,
  all-to-all) parsed from the COMPILED module's HLO — the COMM_TRAFFIC
  account promoted from the bench-only `__graft_entry__` harness into
  the compile log, so the numbers ride every recorded fit and merge
  fleet-wide through the `plan.collective_{ops,bytes}` counters.
- **AotCache**: a per-shape AOT jit cache for training-loop executables
  (the distributed GBDT tree/chunk steps): the FIRST call per shape
  signature lowers and compiles through the compile log — cost analysis
  and collective traffic recorded on the executable actually used, no
  double compile — and later calls dispatch to the cached executable.
- **Resource gauges** (`sample_resource_gauges`): per-device
  `memory_stats()` bytes-in-use/peak and host RSS into gauges, sampled
  on every exposition scrape — fleet scrapes carry memory headroom next
  to latency, and `TelemetryPoller` retains the series. jax is only
  touched if the process already imported it (a scrape must never pay a
  cold jax import on the ingress loop thread).
- **Flight recorder** (`FlightRecorder`): when an SLO verdict
  TRANSITIONS to burning (or on demand via `GET /debug/bundle`), dump a
  bounded, rate-limited debug bundle — span ring JSONL, pending tail
  traces, windowed + cumulative metric snapshots, the SLO verdict,
  recent compile records, device/host memory — to a directory. Rich
  diagnostics captured at the moment of tail-latency distress rather
  than continuously (*CTA-Pipelining*, PAPERS.md). Disabled unless a
  bundle dir is configured (env ``MMLSPARK_TPU_BUNDLE_DIR`` or
  `configure_flight_recorder(bundle_dir=...)`).

`hbm_utilization` also lives here: the bench honesty metric (achieved
bytes/s over measured copy bandwidth) extracted from bench.py so every
future harness computes it the same way.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

from ..reliability.metrics import reliability_metrics
from . import names as tnames
from .spans import get_tracer, wall_now

BUNDLE_DIR_ENV = "MMLSPARK_TPU_BUNDLE_DIR"

_REASON_RE = re.compile(r"[^a-zA-Z0-9_-]+")


# --------------------------------------------------------- compile telemetry
class CompileLog:
    """Bounded per-(fingerprint, shape-bucket) compile bookkeeping.

    `record()` is the single entry point: it feeds the aggregate
    `plan.compiles`/`plan.recompiles` counters and the `plan.compile`
    histogram on the given registry (mergeable fleet-wide: counters sum),
    emits a post-hoc `plan.compile` span (joins the ambient request trace
    when one is sampled), and keeps two bounded stores — an LRU map of
    per-key count/seconds and a deque of the most recent full records
    (what the flight recorder dumps). A key seen again IS a recompile:
    either the plan cache evicted it (pressure) or shape bucketing
    failed (a bug the zero-recompile tests exist to catch)."""

    def __init__(self, max_keys: int = 512, max_records: int = 256,
                 registry=None, tracer=None):
        self._lock = threading.Lock()
        self._keys: OrderedDict = OrderedDict()
        self._records: deque = deque(maxlen=max(int(max_records), 1))
        self._max_keys = max(int(max_keys), 1)
        self._registry = registry
        self._tracer = tracer
        self._compiles = 0
        self._recompiles = 0
        self._seconds = 0.0

    def record(self, fingerprint, bucket, seconds: float,
               analysis: Optional[dict] = None,
               label: Optional[str] = None, registry=None,
               region: Optional[str] = None) -> dict:
        key = (str(fingerprint), bucket)
        if region is None:
            # a compile performed inside a utils.tracing.annotate region
            # tags itself with it — the RooflineLedger's exact join key
            # (never a guessed prefix match)
            try:
                from .profiler import current_region
                region = current_region()
            except Exception:  # noqa: BLE001 - a record without a region
                region = None
        with self._lock:
            ent = self._keys.get(key)
            recompile = ent is not None
            if ent is None:
                if len(self._keys) >= self._max_keys:
                    self._keys.popitem(last=False)
                ent = self._keys[key] = {"count": 0, "seconds": 0.0}
            else:
                self._keys.move_to_end(key)
            ent["count"] += 1
            ent["seconds"] += float(seconds)
            self._compiles += 1
            self._seconds += float(seconds)
            if recompile:
                self._recompiles += 1
            rec = {"fingerprint": str(fingerprint), "bucket": bucket,
                   "seconds": float(seconds), "count": ent["count"],
                   "recompile": recompile, "t": wall_now(),
                   "label": label, "region": region,
                   "analysis": analysis or None}
            self._records.append(rec)
        if registry is None:
            registry = self._registry
        reg = registry if registry is not None else reliability_metrics
        reg.inc(tnames.PLAN_COMPILES)
        if recompile:
            reg.inc(tnames.PLAN_RECOMPILES)
        colls = (analysis or {}).get("collectives") or {}
        if colls:
            # COMM_TRAFFIC-style account rides the fleet-mergeable
            # counters (sums across workers); per-kind detail stays on
            # the record itself
            reg.inc(tnames.PLAN_COLLECTIVE_OPS,
                    sum(int(v.get("ops", 0)) for v in colls.values()))
            reg.inc(tnames.PLAN_COLLECTIVE_BYTES,
                    sum(int(v.get("bytes", 0)) for v in colls.values()))
        reg.observe_ms(tnames.PLAN_COMPILE, float(seconds) * 1000.0)
        tracer = self._tracer if self._tracer is not None else get_tracer()
        tracer.record(tnames.PLAN_COMPILE_SPAN,
                      duration_ms=float(seconds) * 1000.0,
                      attrs={"fingerprint": str(fingerprint)[:16],
                             "bucket": str(bucket),
                             "recompile": recompile})
        return rec

    def per_key(self) -> dict:
        """{"<fingerprint>@<bucket>": {"count", "seconds"}} — the
        autotuner's per-key training rows."""
        with self._lock:
            return {f"{fp}@{bucket}": dict(v)
                    for (fp, bucket), v in self._keys.items()}

    def records(self) -> list:
        """Most recent full records, oldest first (bounded)."""
        with self._lock:
            return list(self._records)

    def stats(self) -> dict:
        with self._lock:
            return {"compiles": self._compiles,
                    "recompiles": self._recompiles,
                    "seconds": self._seconds,
                    "keys": len(self._keys)}

    def clear(self) -> None:
        with self._lock:
            self._keys.clear()
            self._records.clear()
            self._compiles = 0
            self._recompiles = 0
            self._seconds = 0.0


_default_log = CompileLog()


def get_compile_log() -> CompileLog:
    return _default_log


def record_plan_compile(fingerprint, bucket, seconds: float,
                        analysis: Optional[dict] = None,
                        label: Optional[str] = None,
                        registry=None) -> dict:
    """Record one plan build / jit compile into the process-default
    CompileLog (io/plan.py's builder calls this). `registry` routes the
    counters/histogram to a private registry (a ServingTransform built
    with `metrics=`); the recompile bookkeeping stays in the shared log
    either way."""
    return _default_log.record(fingerprint, bucket, seconds,
                               analysis=analysis, label=label,
                               registry=registry)


def compile_stats() -> dict:
    """Aggregate compile counters of the process-default log (bench rides
    this into every BENCH output line)."""
    return _default_log.stats()


# ------------------------------------------------------- collective traffic
_HLO_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
              "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1,
              "pred": 1}

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_COLLECTIVE_RE = re.compile(
    r"=\s*((?:\w+\[[^\]]*\][^ ]*|\([^)]*\)))\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")
_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")


def collective_traffic(hlo_text: str) -> dict:
    """Count collective ops and their payload bytes in compiled HLO:
    {kind: {"ops": n, "bytes": b}}. Bytes are per-device
    per-instruction-execution (instructions inside loops count once —
    pair with analytic per-step formulas where a loop trip count
    matters). Promoted from the bench-only `__graft_entry__` harness so
    every recorded executable carries the COMM_TRAFFIC account."""
    out: dict = {}
    for shapes, kind in _COLLECTIVE_RE.findall(hlo_text):
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(shapes):
            if dt not in _HLO_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _HLO_BYTES[dt]
        ent = out.setdefault(kind, {"ops": 0, "bytes": 0})
        ent["ops"] += 1
        ent["bytes"] += nbytes
    return out


_ALIAS_PARAM_RE = re.compile(r"\(\s*(\d+)\s*,")
_MODULE_NAME_RE = re.compile(r"^HloModule [^,\n]*")


def donation_aliases(hlo_text: str) -> tuple:
    """Flattened parameter numbers donated to outputs, parsed from the
    compiled module header's `input_output_alias={ {0}: (1, {}, ...) }`
    (each entry is `{output}: (param, {param_index}[, kind])`). Returns
    () when the module has no aliasing — shared by the perf ledger and
    the semantic analyzer's donation checker."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return ()
    i = start + len("input_output_alias={")
    depth, j = 1, i
    while j < len(hlo_text) and depth:
        depth += {"{": 1, "}": -1}.get(hlo_text[j], 0)
        j += 1
    return tuple(sorted({int(m) for m in
                         _ALIAS_PARAM_RE.findall(hlo_text[i:j - 1])}))


def hlo_fingerprint(hlo_text: str) -> str:
    """Content hash of an HLO/StableHLO module with the (arbitrary)
    module name normalized away — two lowerings are THE SAME executable
    iff their fingerprints match (the semantic executable-identity
    checker's unit of comparison)."""
    return hashlib.sha1(
        _MODULE_NAME_RE.sub("HloModule m", hlo_text,
                            count=1).encode()).hexdigest()


# ------------------------------------------------------ executable analysis
_COST_FIELDS = (("flops", "flops"),
                ("bytes accessed", "bytes_accessed"),
                ("transcendentals", "transcendentals"),
                ("optimal_seconds", "optimal_seconds"))
_MEM_FIELDS = (("generated_code_size_in_bytes", "generated_code_bytes"),
               ("argument_size_in_bytes", "argument_bytes"),
               ("output_size_in_bytes", "output_bytes"),
               ("alias_size_in_bytes", "alias_bytes"),
               ("temp_size_in_bytes", "temp_bytes"))


def executable_analysis(compiled, collectives: bool = True) -> dict:
    """Cost/memory footprint of a compiled XLA executable, field by
    field, skipping anything the backend omits (the contract: NEVER
    raise, possibly return {}). `peak_bytes` is derived as the sum of
    the reported argument/output/temp/code components — a lower bound
    on live bytes, labeled by construction rather than guessed.
    `collectives` (default on) also parses the optimized HLO for the
    per-kind collective ops/bytes account (`collectives` key, only
    present when the module actually contains collectives)."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend may not implement it
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        for src, dst in _COST_FIELDS:
            v = ca.get(src)
            if isinstance(v, (int, float)):
                out[dst] = float(v)
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        ma = None
    if ma is not None:
        peak = 0.0
        have_peak = False
        for src, dst in _MEM_FIELDS:
            v = getattr(ma, src, None)
            if isinstance(v, (int, float)):
                out[dst] = float(v)
                if dst != "alias_bytes":
                    peak += float(v)
                    have_peak = True
        if have_peak:
            out["peak_bytes"] = peak
    if collectives:
        try:
            traffic = collective_traffic(compiled.as_text())
        except Exception:  # noqa: BLE001 - a backend without HLO text
            traffic = {}
        if traffic:
            out["collectives"] = traffic
    return out


def compile_with_analysis(fn, *args, label: Optional[str] = None,
                          fingerprint: Optional[str] = None,
                          bucket=None, log: Optional[CompileLog] = None,
                          **jit_kwargs):
    """AOT-compile `fn` for `args` (jit -> lower -> compile), timing the
    compile and recording it — with the executable's cost/memory
    analysis — into the compile log. Returns the compiled executable
    (callable with same-shaped args). This is the module-level-jit
    analog of the serving plan build: one call site gives a kernel a
    `plan.compile` span, per-(fingerprint, bucket) counters, and cost
    data the autotuner can learn from."""
    import jax
    t0 = time.perf_counter()
    lowered = jax.jit(fn, **jit_kwargs).lower(*args)
    compiled = lowered.compile()
    seconds = time.perf_counter() - t0
    if bucket is None:
        shapes = []
        for a in args:
            shape = getattr(a, "shape", None)
            shapes.append("x".join(str(d) for d in shape)
                          if shape is not None else type(a).__name__)
        bucket = ",".join(shapes) or "scalar"
    fp = fingerprint or label or getattr(fn, "__qualname__", None) or "jit"
    analysis = executable_analysis(compiled)
    (log if log is not None else _default_log).record(
        fp, bucket, seconds, analysis=analysis, label=label or fp)
    return compiled


class AotCache:
    """Per-shape AOT jit cache that records every compile it performs.

    The serving plan cache gave inference zero-recompile telemetry; the
    training loops still compiled through bare `jax.jit`, invisible to
    the compile log. Wrapping a step function in an AotCache keeps ONE
    compile per (shape, dtype, sharding) signature — the first call per
    signature lowers and compiles (jit -> lower -> compile), records the
    executable's cost analysis AND collective traffic into the compile
    log, and every later call dispatches straight to the cached
    executable. A signature compiled twice (cache pressure, a renamed
    fingerprint) counts `plan.recompiles`, same discipline as serving.

        step = AotCache(train_step_fn, label="gbdt.tree.data_parallel")
        tree, delta = step(bins, grad, hess, fmask, count_w)
    """

    def __init__(self, fn, label: str, fingerprint: Optional[str] = None,
                 log: Optional["CompileLog"] = None, registry=None,
                 max_entries: int = 32, **jit_kwargs):
        self._fn = fn
        self.label = label
        self.fingerprint = fingerprint or label
        self._log = log
        self._registry = registry
        self._jit_kwargs = jit_kwargs
        self._max = max(int(max_entries), 1)
        self._lock = threading.Lock()
        self._compiled: OrderedDict = OrderedDict()
        self._jitted = None

    @property
    def fn(self):
        """The wrapped (un-jitted) step function — the semantic analyzer
        lowers the SAME callable the cache compiles, so its contract
        checks cover the executable that actually runs."""
        return self._fn

    @staticmethod
    def _sig(args) -> tuple:
        sig = []
        for a in args:
            shape = getattr(a, "shape", None)
            if shape is None:
                sig.append(("py", type(a).__name__))
                continue
            sig.append((tuple(shape), str(getattr(a, "dtype", "?")),
                        getattr(a, "sharding", None)))
        return tuple(sig)

    @staticmethod
    def _bucket(args) -> str:
        shapes = []
        for a in args:
            shape = getattr(a, "shape", None)
            shapes.append("x".join(str(d) for d in shape)
                          if shape is not None else type(a).__name__)
        return ",".join(shapes) or "scalar"

    def __call__(self, *args):
        key = self._sig(args)
        with self._lock:
            compiled = self._compiled.get(key)
            if compiled is not None:
                self._compiled.move_to_end(key)
        if compiled is None:
            compiled = self._compile(key, args)
        return compiled(*args)

    def _compile(self, key, args):
        import jax
        with self._lock:
            if self._jitted is None:
                self._jitted = jax.jit(self._fn, **self._jit_kwargs)
            jitted = self._jitted
        # compile OUTSIDE the lock (minutes-long XLA runs must not
        # serialize an unrelated shape's dispatch); two threads racing
        # the same key cost one duplicate compile, last one wins
        t0 = time.perf_counter()
        compiled = jitted.lower(*args).compile()
        seconds = time.perf_counter() - t0
        analysis = executable_analysis(compiled)
        log = self._log if self._log is not None else _default_log
        log.record(self.fingerprint, self._bucket(args), seconds,
                   analysis=analysis, label=self.label,
                   registry=self._registry)
        with self._lock:
            self._compiled[key] = compiled
            while len(self._compiled) > self._max:
                self._compiled.popitem(last=False)
        return compiled


# -------------------------------------------------------------- bench math
def hbm_utilization(bytes_per_sec: float, copy_gbps: float) -> float:
    """Achieved memory traffic over MEASURED copy bandwidth — the bench
    honesty metric (a throughput claim without it can hide a 50x
    memory-bound gap). 0.0 when bandwidth wasn't measured."""
    if copy_gbps is None or copy_gbps <= 0.0:
        return 0.0
    return float(bytes_per_sec) / (float(copy_gbps) * 1e9)


# ---------------------------------------------------------- resource gauges
def _host_rss_bytes() -> int:
    """Current resident set size. /proc on Linux; getrusage peak as the
    portable fallback (labeled the same — headroom math wants 'at least
    this much is held')."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        try:
            import resource
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:  # noqa: BLE001
            return 0


def sample_resource_stats() -> dict:
    """Raw device/host memory snapshot (what memory.json in a flight
    bundle holds). Devices are only enumerated when jax is ALREADY
    imported — sampling must never trigger a cold jax import on the
    serving ingress thread — and `memory_stats()` may be None per device
    (the CPU backend); both degrade to an empty/partial report."""
    out = {"t": wall_now(), "host_rss_bytes": _host_rss_bytes(),
           "devices": []}
    if "jax" in sys.modules:
        try:
            import jax
            for i, d in enumerate(jax.local_devices()):
                try:
                    stats = d.memory_stats()
                except Exception:  # noqa: BLE001
                    stats = None
                out["devices"].append(
                    {"ordinal": i,
                     "platform": getattr(d, "platform", "unknown"),
                     "stats": dict(stats) if stats else None})
        except Exception:  # noqa: BLE001 - a broken backend loses gauges,
            pass           # never a scrape
    return out


def sample_resource_gauges(registry=None) -> dict:
    """Sample device/host memory into gauges on `registry` (default: the
    process registry). Called on every exposition scrape, so
    `scrape_cluster` and the TelemetryPoller carry memory headroom next
    to latency; gauges merge with MAX across workers (worst headroom
    wins, same discipline as queue depth)."""
    reg = registry if registry is not None else reliability_metrics
    stats = sample_resource_stats()
    reg.set_gauge(tnames.HOST_RSS_BYTES, stats["host_rss_bytes"])
    total_use = 0.0
    total_peak = 0.0
    have = False
    for dev in stats["devices"]:
        ms = dev["stats"]
        if not ms:
            continue
        use = ms.get("bytes_in_use")
        peak = ms.get("peak_bytes_in_use")
        if isinstance(use, (int, float)):
            reg.set_gauge(tnames.device_mem_in_use(dev["ordinal"]), use)
            total_use += use
            have = True
        if isinstance(peak, (int, float)):
            reg.set_gauge(tnames.device_mem_peak(dev["ordinal"]), peak)
            total_peak += peak
            have = True
    if have:
        reg.set_gauge(tnames.DEVICE_MEM_BYTES_IN_USE, total_use)
        reg.set_gauge(tnames.DEVICE_MEM_PEAK_BYTES, total_peak)
    return stats


# ---------------------------------------------------------- flight recorder
class FlightRecorder:
    """Bounded, rate-limited debug-bundle dumper.

    Triggers: `SLOEngine.verdict()` notifies `on_verdict` — a verdict
    TRANSITIONING to burning dumps once (staying burning does not; the
    next transition re-arms after it clears); `GET /debug/bundle` calls
    `dump("on-demand")` directly. Both share one rate limit
    (`min_interval_s`, default 60 s) counted under
    `telemetry.bundle.suppressed`, and at most `max_bundles` bundle
    directories are kept (oldest pruned by mtime).

    The dump itself is synchronous and bounded — a span ring, pending
    tail traces, two metric snapshots, the verdict, recent compile
    records, and a memory sample; a few MB of local JSON, written with
    no lock held — deliberately simple enough to run from the /slo or
    /debug handler without a worker thread, so the burn->bundle path is
    deterministic under a seeded fault schedule.

    Disabled (every call a cheap no-op) until a bundle dir is set via
    env ``MMLSPARK_TPU_BUNDLE_DIR`` or `configure(bundle_dir=...)`."""

    def __init__(self, bundle_dir: Optional[str] = None,
                 min_interval_s: float = 60.0, max_bundles: int = 8,
                 window_s: float = 60.0, registry=None, tracer=None,
                 compile_log: Optional[CompileLog] = None,
                 profile_on_burn: bool = False):
        if bundle_dir is None:
            bundle_dir = os.environ.get(BUNDLE_DIR_ENV) or None
        self.bundle_dir = bundle_dir
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = max(int(max_bundles), 1)
        self.window_s = float(window_s)
        # arm a device-profile capture on the same burn transition that
        # dumped the bundle (telemetry/profiler.py; a no-op until a
        # profile dir is configured, absorbed on failure — the bundle
        # outranks the profile)
        self.profile_on_burn = bool(profile_on_burn)
        self._registry = registry
        self._tracer = tracer
        self._compile_log = compile_log
        self._lock = threading.Lock()
        self._seq = 0
        self._last_dump: Optional[float] = None
        # per-trigger-source burn latches ("local" for the process SLO
        # engine, "fleet" for the poller's merged verdict): a burn is one
        # incident per source, and the sources must not mask each other
        self._burn_state: dict = {}

    @property
    def enabled(self) -> bool:
        return self.bundle_dir is not None

    def configure(self, bundle_dir=None, min_interval_s: Optional[float]
                  = None, max_bundles: Optional[int] = None,
                  window_s: Optional[float] = None,
                  profile_on_burn: Optional[bool] = None
                  ) -> "FlightRecorder":
        """Reconfigure in place (None leaves a knob untouched; pass
        bundle_dir="" to disable)."""
        with self._lock:
            if bundle_dir is not None:
                self.bundle_dir = bundle_dir or None
            if min_interval_s is not None:
                self.min_interval_s = float(min_interval_s)
            if max_bundles is not None:
                self.max_bundles = max(int(max_bundles), 1)
            if window_s is not None:
                self.window_s = float(window_s)
            if profile_on_burn is not None:
                self.profile_on_burn = bool(profile_on_burn)
        return self

    # -- triggers ------------------------------------------------------------
    def on_verdict(self, verdict: dict, reason: str = "slo-burn",
                   source: str = "local") -> Optional[dict]:
        """SLO hook: dump once per ok->burning transition, per trigger
        `source` (the process engine and the poller's fleet verdict each
        get their own latch). The latch only engages on a SUCCESSFUL
        dump — a transition whose dump was rate-limit-suppressed or
        failed is retried on the next burning verdict, so the one bundle
        the feature exists for is not silently lost to an earlier
        on-demand dump's rate-limit slot. Never raises."""
        if not self.enabled or not isinstance(verdict, dict):
            return None
        burning = bool(verdict.get("burning"))
        with self._lock:
            fire = burning and not self._burn_state.get(source, False)
            if not burning:
                self._burn_state[source] = False   # incident over: re-arm
        if not fire:
            return None
        manifest = None
        try:
            manifest = self.dump(reason, verdict=verdict)
        except Exception:  # noqa: BLE001 - verdict readers must survive
            manifest = None
        if manifest is not None:
            with self._lock:
                self._burn_state[source] = True
            if self.profile_on_burn:
                # the burn latch also arms ONE device-profile capture:
                # the bundle says WHAT burned, the profile says which op
                # burned it. Rate-limited by the profile session's own
                # slot; absorbed — the successful bundle already latched.
                try:
                    from .profiler import get_profile_session
                    get_profile_session().capture(reason=str(reason))
                except Exception:  # noqa: BLE001 - bundle outranks profile
                    pass
        return manifest

    # -- the dump ------------------------------------------------------------
    def dump(self, reason: str, verdict: Optional[dict] = None
             ) -> Optional[dict]:
        """Write one bundle; returns the manifest dict, or None when the
        recorder is disabled or the rate limit suppressed the dump.
        Raises on a failed write (OSError for an unwritable dir,
        TypeError for unserializable content) — with the rate-limit slot
        ROLLED BACK and the partial bundle dir removed, so a failed dump
        never shadows the next trigger for min_interval_s."""
        if not self.enabled:
            return None
        reg = self._registry if self._registry is not None \
            else reliability_metrics
        now = time.monotonic()
        with self._lock:
            if (self._last_dump is not None
                    and now - self._last_dump < self.min_interval_s):
                suppressed = True
            else:
                suppressed = False
                prev_last = self._last_dump
                self._last_dump = now
                seq = self._seq
                self._seq += 1
        if suppressed:
            reg.inc(tnames.TELEMETRY_BUNDLE_SUPPRESSED)
            return None
        # everything below runs with NO lock held: file I/O must never
        # serialize verdict evaluation or a second trigger's check
        tracer = self._tracer if self._tracer is not None else get_tracer()
        log = self._compile_log if self._compile_log is not None \
            else _default_log
        tag = _REASON_RE.sub("-", str(reason))[:48] or "bundle"
        path = os.path.join(self.bundle_dir,
                            f"bundle-{os.getpid()}-{seq:04d}-{tag}")
        if verdict is None:
            try:
                from .slo import get_engine
                # notify=False: capturing the verdict for the bundle must
                # not re-trigger the recorder mid-dump
                verdict = get_engine().verdict(notify=False)
            except Exception:  # noqa: BLE001 - bundle without a verdict
                verdict = None
        files = []

        def _jsonl(name: str, rows: list) -> None:
            with open(os.path.join(path, name), "w") as f:
                for row in rows:
                    f.write(json.dumps(row) + "\n")
            files.append(name)

        def _json(name: str, obj) -> None:
            with open(os.path.join(path, name), "w") as f:
                json.dump(obj, f, indent=1)
            files.append(name)

        try:
            os.makedirs(path, exist_ok=True)
            _jsonl("spans.jsonl", tracer.finished())
            _jsonl("pending.jsonl", tracer.pending_tail())
            _json("metrics.json", reg.export_state())
            _json("metrics_window.json",
                  reg.export_state(window_s=self.window_s))
            _json("slo.json", verdict)
            _json("compiles.json", {"stats": log.stats(),
                                    "per_key": log.per_key(),
                                    "records": log.records()})
            _json("memory.json", sample_resource_stats())
            # the training-side step-phase breakdown (empty {} on pure
            # serving processes): a burning TRAINING run's bundle then
            # says where its steps' time went
            from .goodput import default_snapshot
            _json("goodput.json", default_snapshot())
            # per-region roofline rows (telemetry/profiler.py): measured
            # region time joined with compile-log cost against peaks —
            # the bundle answers "where does the headroom live" per
            # kernel, not just whole-fit ({} until anything was noted)
            from .profiler import roofline_export
            _json("roofline.json", roofline_export())
            # model-quality state (telemetry/quality.py): per-feature
            # drift rows + streaming-eval state, so a burning bundle
            # says whether the fleet is also still PREDICTING well
            # ({"active": false} on processes without a reference)
            from .quality import export_quality
            _json("quality.json", export_quality())
            # deployment state (telemetry/lineage.py): which model
            # versions this process serves, their roles, per-version
            # metric splits, and the canary readout — a bundle tripped
            # by a canary watch rule NAMES the candidate it indicts
            # ({"versions": [], ...} on processes that never served)
            from .lineage import export_versions
            _json("versions.json", export_versions())
            manifest = {"reason": str(reason), "tag": tag, "seq": seq,
                        "pid": os.getpid(), "t": wall_now(), "path": path,
                        "files": files, "tracer": tracer.stats(),
                        "burning": (verdict or {}).get("burning")}
            _json("manifest.json", manifest)
        except Exception:
            # ANY failed dump — unwritable dir, a non-JSON-serializable
            # span attr or verdict value — gives the rate-limit slot back
            # (a failed dump must not shadow the next trigger) and clears
            # its partial bundle dir, then lets the caller report it
            # (on_verdict absorbs, /debug/bundle 500s)
            with self._lock:
                if self._last_dump == now:
                    self._last_dump = prev_last
            shutil.rmtree(path, ignore_errors=True)
            raise
        self._prune()
        reg.inc(tnames.TELEMETRY_BUNDLE_DUMPS)
        tracer.event(tnames.TELEMETRY_BUNDLE_EVENT, reason=str(reason),
                     path=path)
        return manifest

    def _prune(self) -> None:
        """Keep the newest `max_bundles` bundle dirs (mtime order);
        best-effort — a concurrent prune losing a race is harmless."""
        try:
            entries = [os.path.join(self.bundle_dir, e)
                       for e in os.listdir(self.bundle_dir)
                       if e.startswith("bundle-")]
            entries.sort(key=lambda p: (os.path.getmtime(p), p))
            for stale in entries[:-self.max_bundles]:
                shutil.rmtree(stale, ignore_errors=True)
        except OSError:
            pass


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def configure_flight_recorder(**kwargs) -> FlightRecorder:
    """Configure the process-default flight recorder (see
    `FlightRecorder.configure`)."""
    return get_flight_recorder().configure(**kwargs)


def trigger_bundle(reason: str, verdict: Optional[dict] = None
                   ) -> Optional[dict]:
    """Dump a bundle from the process-default recorder — the public
    one-liner for application code (`trigger_bundle("deploy-canary")`).
    Same contract as `FlightRecorder.dump`: None when disabled or
    rate-limited, OSError on an unwritable bundle dir."""
    return get_flight_recorder().dump(reason, verdict=verdict)
