"""Model-quality observability: streaming distribution sketches, drift
telemetry, and online evaluation on the serving stream.

PRs 7-11 built the systems tier — windowed latency, SLO burn rates, the
flight recorder, roofline attribution — and all of it is blind to what
the models actually PREDICT. The reference ecosystem's third pillar is
model statistics on the same pipeline abstraction (PAPER.md: "model
statistics, LIME interpretability"); this module brings that pillar
online (docs/observability.md "Model-quality observability"):

- **Mergeable streaming sketches** (`FeatureSketch` / `DatasetProfile`):
  per-column distribution profiles — count/mean/M2 via Welford's
  parallel merge, bucketized counts in a
  `reliability.metrics.Histogram` carrying an externally-built grid
  (quantile edges frozen at fit time), and a bounded space-saving top-k
  for categoricals. Two taps: the REFERENCE profile captured at
  ingest/fit time (frozen into the served model's plan payload), and
  the LIVE profile folded on the serving hot path — head-sampled by
  request id (deterministic, the span sampler's own crc32 rule) so the
  batch-of-1 continuous path stays sub-ms (`BENCH_MODE=quality` pins
  the stated overhead budget).
- **Drift scores** (`psi` / `js_divergence` / `drift_scores`):
  Population Stability Index and Jensen-Shannon divergence over the
  SHARED bucket grids. Counts sum across chunks and workers — never
  averaged, the `scrape_cluster`/`merge_verdicts` contract — so fleet
  drift is recomputed from exactly-merged counts, not averaged from
  per-worker scores. Exported as `quality.drift.{col}` gauges (PSI) in
  `/metrics[.json]` plus the `quality.drift.max` roll-up the SLO engine
  and watcher read.
- **Online evaluation** (`StreamingEvaluator`): a delayed-label join
  keyed on the request id (== trace id == `X-Request-Id`, PR 5) feeding
  the SAME mergeable metric states batch `ComputeModelStatistics`
  finalizes (`train.metrics.ConfusionState` / `RegressionState` — one
  kernel, so batch and streaming cannot diverge). Label-stream chaos is
  counted, never crashed: out-of-order labels join late
  (`quality.labels.late`), duplicates are dropped once counted
  (`quality.labels.dup`), and labels arriving after their prediction
  aged out of the bounded join window count `quality.labels.dropped`
  (seeded via the `quality.label` fault site).
- **Closing the loop**: `telemetry.slo.quality_objectives()` declares a
  drift ceiling + metric floor (merging worst-worker, never averaged),
  `quality_watch_rules()` arms the live watcher on the drift series,
  every flight bundle carries `quality.json`, and `GET /quality` rides
  `EXPOSITION_PATHS` on serving (both transports), trainer exposition,
  and the registry; `scrape_cluster(quality=True)` merges the per-worker
  exports exactly.

Everything here is passive observability: disabled (one boolean test per
batch) until a reference profile is installed — `serve_pipeline` does it
automatically for models fitted with `quality_profile=True` (the GBDT
estimators' default).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from ..reliability.metrics import Histogram, reliability_metrics
from . import names as tnames
from .spans import head_sampled

NUMERIC = "numeric"
CATEGORICAL = "categorical"

# profile-capture bounds: reference grids come from a bounded head sample
# (quantile edges need one sort, not the dataset)
DEFAULT_BUCKETS = 10
DEFAULT_TOPK = 32
MAX_REFERENCE_ROWS = 65536

# additive (Laplace) pseudo-count per bucket in the drift math: a bucket
# the live sample merely hasn't hit yet must read as "rare", not as a
# near-zero probability whose log-ratio dominates the score — the classic
# small-sample PSI blow-up
_SMOOTH = 0.5


# ------------------------------------------------------------------ moments
class _Moments:
    """Welford/Chan mergeable moments: n, mean, M2 (sum of squared
    deviations). `update` folds an array vectorized; `merge` is the
    shared `utils.stats.merge_moments` combine (one kernel with
    `train.metrics.RegressionState`) — exact over any chunking of the
    same rows up to float association."""

    __slots__ = ("n", "mean", "m2")

    def __init__(self, n: int = 0, mean: float = 0.0, m2: float = 0.0):
        self.n = int(n)
        self.mean = float(mean)
        self.m2 = float(m2)

    def update(self, values: np.ndarray) -> "_Moments":
        v = np.asarray(values, dtype=np.float64).ravel()
        v = v[np.isfinite(v)]
        if v.size == 0:
            return self
        return self.merge(_Moments(int(v.size), float(v.mean()),
                                   float(((v - v.mean()) ** 2).sum())))

    def merge(self, other: "_Moments") -> "_Moments":
        from ..utils.stats import merge_moments
        self.n, self.mean, self.m2 = merge_moments(
            self.n, self.mean, self.m2, other.n, other.mean, other.m2)
        return self

    @property
    def variance(self) -> float:
        return self.m2 / self.n if self.n else 0.0

    def state(self) -> dict:
        return {"n": self.n, "mean": self.mean, "m2": self.m2}

    @classmethod
    def from_state(cls, state: dict) -> "_Moments":
        return cls(state["n"], state["mean"], state["m2"])


# ------------------------------------------------------------------ sketches
class FeatureSketch:
    """One column's mergeable streaming profile.

    Numeric columns hold Welford moments plus bucket counts in a
    `reliability.metrics.Histogram` built over an EXTERNAL grid (the
    quantile edges of the reference sample) — its `state()/from_state()`
    round-trip and `merge_state` count-sum are the mergeable form, shared
    with the latency histograms' scrape merge. Categorical columns hold a
    bounded space-saving top-k counter (capacity `topk`; an evicted key's
    successor inherits its count, the classic overestimate-never-miss
    trade) plus the exact total.
    """

    def __init__(self, name: str, kind: str = NUMERIC,
                 edges: Optional[tuple] = None, topk: int = DEFAULT_TOPK):
        if kind not in (NUMERIC, CATEGORICAL):
            raise ValueError(f"kind must be numeric|categorical, got {kind!r}")
        self.name = name
        self.kind = kind
        self._lock = threading.Lock()
        if kind == NUMERIC:
            self.edges = tuple(float(e) for e in (edges or (0.0,)))
            self.hist = Histogram(f"quality.{name}", bounds=self.edges)
            self.moments = _Moments()
            self._edges_arr = np.asarray(self.edges, dtype=np.float64)
        else:
            self.topk = max(int(topk), 1)
            self.counts: dict = {}
            self.total = 0

    # -- folding --------------------------------------------------------------
    def observe(self, values) -> int:
        """Fold an array of values; returns the number folded. Vectorized:
        one searchsorted + bincount per call, merged into the histogram
        through its public mergeable-state kernel (never per-row
        bisects)."""
        v = np.asarray(values).ravel()
        if v.size == 0:
            return 0
        if self.kind == CATEGORICAL:
            keys, counts = np.unique(v, return_counts=True)
            with self._lock:
                for key, c in zip(keys.tolist(), counts.tolist()):
                    self._add_key(str(key), int(c))
                self.total += int(v.size)
            return int(v.size)
        v = np.asarray(v, dtype=np.float64)
        v = v[np.isfinite(v)]
        if v.size == 0:
            return 0
        # np.searchsorted(side="right") == bisect_right: the same bucket
        # rule Histogram.observe_ms applies one value at a time
        idx = np.searchsorted(self._edges_arr, v, side="right")
        counts = np.bincount(idx, minlength=len(self.edges) + 1)
        self.hist.merge_state({
            "bounds": list(self.edges),
            "counts": counts.tolist(), "count": int(v.size),
            "sum_ms": float(v.sum()), "min_ms": float(v.min()),
            "max_ms": float(v.max())})
        with self._lock:
            self.moments.update(v)
        return int(v.size)

    def _add_key(self, key: str, count: int) -> None:
        """Space-saving insert (lock held): a new key past capacity evicts
        the current minimum and inherits its count — frequent keys can be
        overestimated, never silently missed."""
        if key in self.counts:
            self.counts[key] += count
            return
        if len(self.counts) < self.topk:
            self.counts[key] = count
            return
        min_key = min(sorted(self.counts), key=self.counts.__getitem__)
        floor = self.counts.pop(min_key)
        self.counts[key] = floor + count

    # -- merge / state --------------------------------------------------------
    def merge(self, other) -> "FeatureSketch":
        """Exact fold of another sketch (or its state dict): bucket/topk
        counts sum, moments Chan-merge — never averaged."""
        state = other.state() if isinstance(other, FeatureSketch) else other
        if state["kind"] != self.kind:
            raise ValueError(f"cannot merge {state['kind']} into "
                             f"{self.kind} sketch {self.name!r}")
        if self.kind == CATEGORICAL:
            with self._lock:
                for key in sorted(state["counts"]):
                    self._add_key(str(key), int(state["counts"][key]))
                self.total += int(state["total"])
            return self
        self.hist.merge_state(state["hist"])
        with self._lock:
            self.moments.merge(_Moments.from_state(state["moments"]))
        return self

    def state(self) -> dict:
        if self.kind == CATEGORICAL:
            with self._lock:
                return {"name": self.name, "kind": self.kind,
                        "topk": self.topk, "counts": dict(self.counts),
                        "total": self.total}
        with self._lock:
            moments = self.moments.state()
        return {"name": self.name, "kind": self.kind,
                "edges": list(self.edges), "hist": self.hist.state(),
                "moments": moments}

    @classmethod
    def from_state(cls, state: dict) -> "FeatureSketch":
        if state["kind"] == CATEGORICAL:
            sk = cls(state["name"], CATEGORICAL, topk=state["topk"])
            sk.counts = {str(k): int(v) for k, v in state["counts"].items()}
            sk.total = int(state["total"])
            return sk
        sk = cls(state["name"], NUMERIC, edges=tuple(state["edges"]))
        sk.hist = Histogram.from_state(f"quality.{state['name']}",
                                       state["hist"])
        sk.moments = _Moments.from_state(state["moments"])
        return sk

    def spawn_empty(self) -> "FeatureSketch":
        """A fresh sketch over the SAME grid/keys-capacity — the live tap
        twin of a frozen reference sketch (shared grid is what makes the
        drift counts comparable)."""
        if self.kind == CATEGORICAL:
            return FeatureSketch(self.name, CATEGORICAL, topk=self.topk)
        return FeatureSketch(self.name, NUMERIC, edges=self.edges)

    @property
    def count(self) -> int:
        if self.kind == CATEGORICAL:
            return self.total
        return self.hist.count

    def bucket_counts(self) -> np.ndarray:
        """Counts over the shared grid (numeric) — drift math input."""
        return np.asarray(self.hist.state()["counts"], dtype=np.float64)


def build_numeric_sketch(name: str, values, n_buckets: int = DEFAULT_BUCKETS,
                         max_rows: int = MAX_REFERENCE_ROWS,
                         observe: bool = True) -> FeatureSketch:
    """Reference-time constructor: quantile bucket edges from a bounded
    head sample of `values`, then (with `observe`) the sample folded in
    — `observe=False` freezes the grid only, for callers that fold rows
    themselves (the chunked ingest tap; folding here too would profile
    the sample twice). The resulting grid is the frozen contract every
    live sketch and every worker shares — drift is only defined over
    identical grids."""
    v = np.asarray(values, dtype=np.float64).ravel()[:max(int(max_rows), 1)]
    finite = v[np.isfinite(v)]
    if finite.size == 0:
        edges: tuple = (0.0,)
    else:
        qs = np.linspace(0.0, 1.0, max(int(n_buckets), 2) + 1)[1:-1]
        edges = tuple(np.unique(np.quantile(finite, qs)).tolist())
        if not edges:
            edges = (float(finite[0]),)
    sk = FeatureSketch(name, NUMERIC, edges=edges)
    if observe:
        sk.observe(v)
    return sk


# --------------------------------------------------------------- drift math
def _normalize(counts, smooth: float = _SMOOTH) -> np.ndarray:
    c = np.asarray(counts, dtype=np.float64)
    c = np.maximum(c, 0.0) + smooth
    return c / c.sum()


def psi(ref_counts, live_counts, smooth: float = _SMOOTH) -> float:
    """Population Stability Index over two count vectors on ONE shared
    grid: sum((q - p) * ln(q / p)) with an additive `smooth` pseudo-count
    per bucket (Laplace) — an empty bucket reads as rare, not as a
    log-ratio singularity, so a few dozen live samples score noise-level
    drift instead of tripping the SLO on startup. Rule-of-thumb scale:
    < 0.1 stable, 0.1-0.25 drifting, > 0.25 shifted (the bound
    `slo.quality_objectives` defaults to)."""
    p = _normalize(ref_counts, smooth)
    q = _normalize(live_counts, smooth)
    return float(((q - p) * np.log(q / p)).sum())


def js_divergence(ref_counts, live_counts,
                  smooth: float = _SMOOTH) -> float:
    """Jensen-Shannon divergence (base 2, in [0, 1]) over two count
    vectors on one shared grid — bounded and symmetric where PSI is
    neither, so the pair brackets the drift claim. Same Laplace
    smoothing as `psi`."""
    p = _normalize(ref_counts, smooth)
    q = _normalize(live_counts, smooth)
    m = 0.5 * (p + q)
    kl_pm = (p * np.log2(p / m)).sum()
    kl_qm = (q * np.log2(q / m)).sum()
    return float(0.5 * kl_pm + 0.5 * kl_qm)


def _categorical_vectors(ref: dict, live: dict,
                         ref_total: int, live_total: int):
    """Aligned count vectors over the union of top-k keys plus an
    `other` bucket holding each side's residual mass (total minus the
    tracked keys) — both sides see the same support."""
    keys = sorted(set(ref) | set(live))
    r = [float(ref.get(k, 0)) for k in keys]
    lv = [float(live.get(k, 0)) for k in keys]
    r.append(max(float(ref_total) - sum(r), 0.0))
    lv.append(max(float(live_total) - sum(lv), 0.0))
    return np.asarray(r), np.asarray(lv)


def drift_scores(reference: "DatasetProfile",
                 live: "DatasetProfile") -> dict:
    """{col: {psi, js, ref_count, live_count}} over every column both
    profiles carry. Grids are shared by construction (`spawn_live`); a
    column whose grids diverged anyway (mixed profile versions) is
    reported with `grid_mismatch` instead of a silently-wrong score."""
    out: dict = {}
    for name in sorted(reference.columns):
        ref = reference.columns[name]
        lv = live.columns.get(name)
        if lv is None or lv.kind != ref.kind:
            continue
        row = {"kind": ref.kind, "ref_count": int(ref.count),
               "live_count": int(lv.count)}
        if lv.count == 0:
            # no live traffic folded yet: no claim, not "zero drift"
            row["psi"] = None
            row["js"] = None
            out[name] = row
            continue
        if ref.kind == CATEGORICAL:
            r, q = _categorical_vectors(ref.counts, lv.counts,
                                        ref.total, lv.total)
        else:
            if tuple(ref.edges) != tuple(lv.edges):
                row["grid_mismatch"] = True
                out[name] = row
                continue
            r, q = ref.bucket_counts(), lv.bucket_counts()
        row["psi"] = psi(r, q)
        row["js"] = js_divergence(r, q)
        out[name] = row
    return out


# ----------------------------------------------------------------- profiles
def matrix_columns(x, prefix: str = "f") -> dict:
    """Expand an (n, F) features matrix into the canonical per-slot
    column names (`f0`..`f{F-1}`) the reference and live taps both use —
    one naming rule so the grids line up."""
    x = np.asarray(x)
    if x.ndim == 1:
        return {f"{prefix}0": x}
    return {f"{prefix}{i}": x[:, i] for i in range(x.shape[1])}


class DatasetProfile:
    """A set of named `FeatureSketch`es — one dataset's distribution
    profile. `fit()` freezes grids from reference data; `spawn_live()`
    twins it with empty sketches over the SAME grids; `merge()`/`state()`
    are the exact chunk/fleet fold (counts sum, never averaged)."""

    def __init__(self, columns: Optional[dict] = None):
        self.columns: dict = dict(columns or {})

    @classmethod
    def fit(cls, columns: dict, n_buckets: int = DEFAULT_BUCKETS,
            categorical=(), topk: int = DEFAULT_TOPK,
            max_rows: int = MAX_REFERENCE_ROWS,
            observe: bool = True) -> "DatasetProfile":
        """Build the reference profile from named column arrays: numeric
        columns get quantile bucket grids (and, with `observe`, the
        bounded head sample folded in); names listed in `categorical` get
        bounded top-k counters. `observe=False` freezes grids only — the
        caller folds rows itself (e.g. `data.pipeline.profile_columns`
        chunk by chunk)."""
        cat = set(str(c) for c in categorical)
        prof = cls()
        for name in sorted(columns):
            v = np.asarray(columns[name]).ravel()
            if name in cat:
                sk = FeatureSketch(name, CATEGORICAL, topk=topk)
                if observe:
                    sk.observe(v[:max_rows])
            else:
                sk = build_numeric_sketch(name, v, n_buckets=n_buckets,
                                          max_rows=max_rows,
                                          observe=observe)
            prof.columns[name] = sk
        return prof

    def spawn_live(self) -> "DatasetProfile":
        return DatasetProfile({name: sk.spawn_empty()
                               for name, sk in self.columns.items()})

    def observe(self, name: str, values) -> int:
        sk = self.columns.get(name)
        if sk is None:
            return 0
        return sk.observe(values)

    def merge(self, other) -> "DatasetProfile":
        state = other.state() if isinstance(other, DatasetProfile) else other
        for name in sorted(state.get("columns", {})):
            st = state["columns"][name]
            sk = self.columns.get(name)
            if sk is None:
                self.columns[name] = FeatureSketch.from_state(st)
            else:
                sk.merge(st)
        return self

    def state(self) -> dict:
        return {"columns": {name: sk.state()
                            for name, sk in sorted(self.columns.items())}}

    @classmethod
    def from_state(cls, state: dict) -> "DatasetProfile":
        return cls({name: FeatureSketch.from_state(st)
                    for name, st in state.get("columns", {}).items()})

    @property
    def count(self) -> int:
        return max((sk.count for sk in self.columns.values()), default=0)


# -------------------------------------------------- online evaluation (join)
class StreamingEvaluator:
    """Delayed-label join + mergeable streaming evaluation.

    `record_prediction(request_id, value)` parks the served value in a
    bounded FIFO window; `record_label(request_id, label)` joins against
    it and folds the pair into the SAME mergeable metric state batch
    `ComputeModelStatistics` finalizes (`train.metrics.ConfusionState` /
    `RegressionState` — streaming and batch share one kernel by
    construction). The label stream is hostile by assumption and every
    anomaly is COUNTED, never crashed:

    - a label arriving BEFORE its prediction parks in a bounded buffer
      and joins when the prediction lands (`quality.labels.late`);
    - a second label for an already-joined id is ignored once counted
      (`quality.labels.dup`);
    - a label whose prediction aged out of the join window — or whose
      parked slot was evicted — counts `quality.labels.dropped`.

    `kind="auto"` resolves on the first join (both sides integer-like =>
    classification, the `ComputeModelStatistics` heuristic); AUC-style
    rank metrics need the full score ordering and stay batch-only.
    HOSTILE values honor the same contract: a non-finite label/prediction
    or a classification label outside [0, MAX_CLASSES) is counted
    dropped, never folded — one label of 1e9 must not allocate a
    1e9-class confusion matrix (or wrap a negative index into it).
    Chaos: the `quality.label` fault site fires per label when an
    injector is attached — kind ``drop`` loses the label pre-join
    (counted dropped), so seeded schedules replay identical anomaly
    sequences.

    Joined pairs are also PUSHED: `subscribe(fn)` (or `on_join=`)
    registers a `fn(request_id, prediction, label)` callback fired once
    per successful join, outside the evaluator lock. Fan-out is bounded
    (`MAX_SUBSCRIBERS`) and a raising subscriber is counted
    (`quality.join.subscriber_errors`) and absorbed — a bad consumer
    can never kill the evaluator or undo the join. This is the label
    feed an online learner trains from."""

    # classification joins outside [0, MAX_CLASSES) are invalid input,
    # not a request to grow the count matrix without bound
    MAX_CLASSES = 256
    # joined-pair fan-out is bounded like every other buffer here
    MAX_SUBSCRIBERS = 8

    def __init__(self, kind: str = "auto", max_pending: int = 4096,
                 max_parked: int = 1024, registry=None, faults=None,
                 on_join=None):
        if kind not in ("auto", "classification", "regression"):
            raise ValueError(
                "kind must be auto|classification|regression")
        self.kind = kind
        self.max_pending = max(int(max_pending), 1)
        self.max_parked = max(int(max_parked), 1)
        self._metrics = registry if registry is not None \
            else reliability_metrics
        self._faults = faults
        self._subscribers: list = []
        if on_join is not None:
            self.subscribe(on_join)
        self._lock = threading.Lock()
        self._resolved: Optional[str] = None if kind == "auto" else kind
        self._pending: OrderedDict = OrderedDict()   # id -> prediction
        self._parked: OrderedDict = OrderedDict()    # id -> label
        self._evicted: OrderedDict = OrderedDict()   # bounded id tombstones
        self._joined: OrderedDict = OrderedDict()    # bounded joined ids
        self._cls = None
        self._reg = None
        self._joined_total = 0

    # -- join fan-out ---------------------------------------------------------
    def subscribe(self, callback):
        """Register `fn(request_id, prediction, label)`, fired once per
        successful join. Bounded: past MAX_SUBSCRIBERS is a config
        error, not a silent drop."""
        if not callable(callback):
            raise TypeError("on_join subscriber must be callable")
        if len(self._subscribers) >= self.MAX_SUBSCRIBERS:
            raise ValueError(
                f"subscriber fan-out is bounded at {self.MAX_SUBSCRIBERS}")
        self._subscribers.append(callback)
        return callback

    def _notify_join(self, rid: str, pred: float, label: float) -> None:
        """Fan a joined pair out to subscribers — called with the lock
        RELEASED (a subscriber may call back into the evaluator). A
        raising subscriber is counted and absorbed; the join stands."""
        for fn in list(self._subscribers):
            try:
                fn(rid, pred, label)
            except Exception:
                self._metrics.inc(tnames.QUALITY_JOIN_SUBSCRIBER_ERRORS)

    # -- value plumbing -------------------------------------------------------
    @staticmethod
    def _scalar(value) -> float:
        arr = np.asarray(value, dtype=np.float64)
        if arr.size == 1:
            return float(arr.reshape(()))
        # vector outputs (probabilities): the predicted class
        return float(arr.argmax())

    def _resolve(self, pred: float, label: float) -> str:
        if self._resolved is None:
            int_like = (float(pred).is_integer()
                        and float(label).is_integer()
                        and 0 <= label <= 100 and 0 <= pred <= 100)
            self._resolved = "classification" if int_like else "regression"
        return self._resolved

    def _join(self, rid: str, pred: float, label: float) -> bool:
        """Fold one (prediction, label) pair — lock held. Returns False
        (caller counts the label dropped) for values that cannot be
        folded: non-finite on either side, or a classification id
        outside [0, MAX_CLASSES)."""
        from ..train.metrics import ConfusionState, RegressionState
        if not (np.isfinite(pred) and np.isfinite(label)):
            return False
        kind = self._resolve(pred, label)
        if kind == "classification":
            yi, pi = int(round(label)), int(round(pred))
            if not (0 <= yi < self.MAX_CLASSES
                    and 0 <= pi < self.MAX_CLASSES):
                return False
            if self._cls is None:
                self._cls = ConfusionState(2)
            self._cls.update([yi], [pi])
        else:
            if self._reg is None:
                self._reg = RegressionState()
            self._reg.update([label], [pred])
        self._joined[rid] = None
        while len(self._joined) > self.max_pending:
            self._joined.popitem(last=False)
        self._joined_total += 1
        self._metrics.inc(tnames.QUALITY_LABELS_JOINED)
        self._set_eval_gauges()
        return True

    def _set_eval_gauges(self) -> None:
        """Current metric values as gauges (lock held; the registry uses
        its own lock — quality -> registry is the one nesting order).
        Counter-side rates (`quality.labels.*`) carry the windowed view;
        the gauges are the last-value summary the SLO floor reads."""
        for name, value in sorted(self._metric_values().items()):
            self._metrics.set_gauge(tnames.quality_eval(name), value)

    def _metric_values(self) -> dict:
        if self._resolved == "classification" and self._cls is not None:
            vals = self._cls.binary()
            return {"accuracy": float(vals["accuracy"]),
                    "precision": float(vals["precision"]),
                    "recall": float(vals["recall"])}
        if self._resolved == "regression" and self._reg is not None:
            vals = self._reg.metrics()
            return {"rmse": float(vals["rmse"]), "mae": float(vals["mae"])}
        return {}

    # -- the join -------------------------------------------------------------
    def record_prediction(self, request_id: str, value) -> str:
        v = self._scalar(value)
        with self._lock:
            label = self._parked.pop(request_id, None)
            if label is not None:
                # out-of-order: the label beat its prediction here
                if not self._join(request_id, v, label):
                    self._metrics.inc(tnames.QUALITY_LABELS_DROPPED)
                    return "dropped"
                self._metrics.inc(tnames.QUALITY_LABELS_LATE)
            elif request_id in self._joined:
                return "joined"
            else:
                self._pending[request_id] = v
                while len(self._pending) > self.max_pending:
                    old, _ = self._pending.popitem(last=False)
                    self._evicted[old] = None
                    while len(self._evicted) > self.max_pending:
                        self._evicted.popitem(last=False)
                return "pending"
        # late join succeeded: fan out with the lock released
        self._notify_join(request_id, v, label)
        return "late-join"

    def record_label(self, request_id: str, label) -> str:
        if self._faults is not None:
            fault = self._faults.fire("quality.label")
            if fault is not None and fault.kind == "drop":
                # injected label loss: the join window never sees it
                self._metrics.inc(tnames.QUALITY_LABELS_DROPPED)
                return "dropped"
        try:
            y = self._scalar(label)
        except (TypeError, ValueError):
            # unparsable label (a string, a ragged object) — counted,
            # never crashed
            self._metrics.inc(tnames.QUALITY_LABELS_DROPPED)
            return "dropped"
        with self._lock:
            if request_id in self._joined:
                self._metrics.inc(tnames.QUALITY_LABELS_DUP)
                return "dup"
            pred = self._pending.pop(request_id, None)
            if pred is not None:
                if not self._join(request_id, pred, y):
                    # unfoldable (non-finite / out-of-range) label:
                    # counted, never crashed — the contract
                    self._metrics.inc(tnames.QUALITY_LABELS_DROPPED)
                    return "dropped"
                pass
            elif request_id in self._evicted:
                # label-after-eviction: the prediction aged out of the
                # bounded window before its label arrived
                self._evicted.pop(request_id, None)
                self._metrics.inc(tnames.QUALITY_LABELS_DROPPED)
                return "dropped"
            else:
                # label BEFORE prediction: park it for the late join
                self._parked[request_id] = y
                while len(self._parked) > self.max_parked:
                    self._parked.popitem(last=False)
                    self._metrics.inc(tnames.QUALITY_LABELS_DROPPED)
                return "parked"
        # joined inside the lock: fan out with it released
        self._notify_join(request_id, pred, y)
        return "joined"

    # -- read side ------------------------------------------------------------
    def metrics(self) -> dict:
        with self._lock:
            return self._metric_values()

    def export(self) -> dict:
        with self._lock:
            out = {"kind": self._resolved, "joined": self._joined_total,
                   "pending": len(self._pending),
                   "parked": len(self._parked),
                   "metrics": self._metric_values()}
            if self._cls is not None:
                out["confusion"] = self._cls.state()
            if self._reg is not None:
                out["regression"] = self._reg.state()
        return out

    def merge_export(self, export: dict) -> "StreamingEvaluator":
        """Fold another evaluator's export (counts sum — the fleet
        merge; `pending`/`parked` are per-worker live state and do not
        merge)."""
        from ..train.metrics import ConfusionState, RegressionState
        with self._lock:
            if export.get("kind") and self._resolved is None:
                self._resolved = export["kind"]
            if "confusion" in export:
                other = ConfusionState.from_state(export["confusion"])
                if self._cls is None:
                    self._cls = other
                else:
                    self._cls.merge(other)
            if "regression" in export:
                other = RegressionState.from_state(export["regression"])
                if self._reg is None:
                    self._reg = other
                else:
                    self._reg.merge(other)
            self._joined_total += int(export.get("joined", 0))
        return self


# ------------------------------------------------------------------ monitor
class QualityMonitor:
    """The process-wide quality tap: reference profile + live profile +
    streaming evaluator, folded from the serving hot path and read by
    `/quality`, the drift gauges, the SLO engine, and the flight
    recorder. Inactive (one boolean test per serving batch) until a
    reference is installed."""

    def __init__(self, registry=None):
        self._registry = registry if registry is not None \
            else reliability_metrics
        self._lock = threading.Lock()
        self.reference: Optional[DatasetProfile] = None
        self.live: Optional[DatasetProfile] = None
        self.evaluator = StreamingEvaluator(registry=registry)
        self.sample = 1.0
        self.labels_enabled = True
        # gauge-publication floor: PSI over a handful of live rows is
        # sampling noise, not drift — a column's gauge only publishes
        # once its live sketch holds this many rows (the export still
        # carries every row's score for drill-down; no-data burns 0 in
        # the SLO, so a fresh worker never starts life "burning")
        self.min_live = 100
        # id-less callers still honor the sample rate via systematic
        # row-count sampling (every round(1/sample)-th row, offset
        # carried across batches)
        self._row_cursor = 0
        self._active = False

    @property
    def active(self) -> bool:
        return self._active

    def set_reference(self, profile, reset_live: bool = True
                      ) -> "QualityMonitor":
        """Install the frozen reference profile (a `DatasetProfile` or
        its `state()` dict — the form the GBDT estimators stash on fitted
        models) and spawn the live twin over the same grids.

        `ServingTransform.install_model` calls this on every hot-swap
        AFTER the version registry freezes the incumbent's canary
        baseline (telemetry/lineage.py) — the baseline must read the OLD
        reference's drift, and the reset below is what clears the old
        model's stale `quality.drift.*` gauges from the swap onward."""
        prof = (profile if isinstance(profile, DatasetProfile)
                else DatasetProfile.from_state(profile))
        with self._lock:
            self.reference = prof
            if reset_live or self.live is None:
                self.live = prof.spawn_live()
            self._active = True
        # a fresh reference invalidates every published drift gauge: the
        # old model's drift must not keep an SLO burning (or a watcher
        # tripped) against a model no longer being served — gauges
        # republish once the new live profile crosses min_live
        self._registry.reset("quality.drift")
        return self

    def configure(self, sample: Optional[float] = None,
                  labels: Optional[bool] = None,
                  min_live: Optional[int] = None,
                  evaluator: Optional[StreamingEvaluator] = None
                  ) -> "QualityMonitor":
        with self._lock:
            if sample is not None:
                self.sample = float(sample)
            if labels is not None:
                self.labels_enabled = bool(labels)
            if min_live is not None:
                self.min_live = max(int(min_live), 1)
            if evaluator is not None:
                self.evaluator = evaluator
        return self

    # -- the serving tap ------------------------------------------------------
    def observe_serving(self, features, predictions,
                        request_ids: Optional[list] = None) -> int:
        """Fold one served batch: predictions enter the label-join window
        (all rows — one dict insert each), and the live sketches fold a
        HEAD-SAMPLED subset — the decision is `crc32(request_id)`, the
        span sampler's own deterministic rule, so independent workers
        agree per id and the continuous batch-of-1 path pays one crc32 +
        (rate-proportionally) one sketch fold. Returns rows folded into
        the sketches."""
        if not self._active:
            return 0
        preds = np.asarray(predictions)
        if self.labels_enabled and request_ids is not None:
            for i, rid in enumerate(request_ids):
                if rid is not None:
                    self.evaluator.record_prediction(rid, preds[i])
        if self.sample <= 0.0:
            return 0
        n_rows = preds.shape[0] if preds.ndim else 1
        if request_ids is None:
            # no ids to hash: systematic sampling at the SAME rate — an
            # id-less transport must not silently fold 100% of traffic
            if self.sample >= 1.0:
                sel = list(range(n_rows))
            else:
                stride = max(int(round(1.0 / self.sample)), 1)
                with self._lock:
                    cursor = self._row_cursor
                    self._row_cursor = (cursor + n_rows) % stride
                sel = [i for i in range(n_rows)
                       if (cursor + i) % stride == 0]
        else:
            sel = [i for i, rid in enumerate(request_ids)
                   if rid is not None and head_sampled(rid, self.sample)]
        if not sel:
            return 0
        live = self.live
        if isinstance(features, dict):
            cols: dict = {}
            for cname in sorted(features):
                arr = np.asarray(features[cname])
                if arr.ndim >= 2:
                    cols.update(matrix_columns(arr))
                else:
                    cols[cname] = arr
        else:
            cols = matrix_columns(features)
        folded = 0
        for cname in sorted(cols):
            if cname in live.columns:
                folded = max(folded,
                             live.observe(cname, np.take(cols[cname], sel,
                                                         axis=0)))
        if "prediction" in live.columns:
            live.observe("prediction", np.take(preds, sel, axis=0))
        if folded:
            self._registry.inc(tnames.QUALITY_SKETCH_ROWS, folded)
        return folded

    def record_label(self, request_id: str, label) -> str:
        """The application-side half of the delayed-label join (ids are
        the `X-Request-Id` serving returned)."""
        return self.evaluator.record_label(request_id, label)

    # -- read side ------------------------------------------------------------
    def drift(self) -> dict:
        with self._lock:
            ref, live = self.reference, self.live
        if ref is None or live is None:
            return {}
        return drift_scores(ref, live)

    def refresh_gauges(self, registry=None) -> dict:
        """Compute drift and publish the `quality.drift.{col}` (PSI)
        gauges plus the `quality.drift.max` roll-up — called on every
        exposition scrape so `/metrics[.json]`, the poller series, and
        the SLO engine all read fresh drift."""
        rows = self.drift()
        reg = registry if registry is not None else self._registry
        # republish from a clean slate: a gauge published on an earlier
        # refresh must not outlive the column (or model) that produced
        # it — stale drift is exactly the false page this tier exists
        # to prevent
        reg.reset("quality.drift")
        if not rows:
            return rows
        worst = 0.0
        have = False
        for col in sorted(rows):
            value = rows[col].get("psi")
            if value is None or rows[col]["live_count"] < self.min_live:
                # below the publication floor: small-sample PSI is noise
                # — the row stays in the export, the gauge stays absent
                continue
            reg.set_gauge(tnames.quality_drift(col), float(value))
            worst = max(worst, float(value))
            have = True
        if have:
            reg.set_gauge(tnames.QUALITY_DRIFT_MAX, worst)
        return rows

    def export(self) -> dict:
        """The `/quality` + flight-bundle payload: reference/live sketch
        states (the exactly-mergeable form), per-column drift rows, and
        the streaming-eval state."""
        with self._lock:
            active = self._active
            ref = self.reference.state() if self.reference else None
            live = self.live.state() if self.live else None
            sample = self.sample
        out = {"active": active, "sample": sample,
               "drift": self.drift(), "eval": self.evaluator.export()}
        if ref is not None:
            out["reference"] = ref
        if live is not None:
            out["live"] = live
        return out


def _grids_compatible(live: "DatasetProfile", state: dict) -> bool:
    """Can `state` fold into `live` exactly? Shared columns must agree on
    kind and (numeric) bucket edges — checked before any fold so an
    incompatible worker contributes nothing rather than a partial sum."""
    for name in sorted(state.get("columns", {})):
        st = state["columns"][name]
        sk = live.columns.get(name)
        if sk is None:
            continue
        if st.get("kind") != sk.kind:
            return False
        if sk.kind == NUMERIC and list(st.get("edges", ())) != \
                list(sk.edges):
            return False
    return True


def merge_quality_exports(exports: list) -> Optional[dict]:
    """Fleet merge of per-worker `/quality` exports: LIVE sketch counts
    sum exactly across workers (never averaged), eval states fold through
    the same `ConfusionState`/`RegressionState` merges, drift is
    RECOMPUTED from the merged counts against the (shared) reference —
    the `merge_verdicts` discipline applied to semantics."""
    exports = [e for e in exports if e and e.get("active")]
    if not exports:
        return None
    reference = None
    live = None
    evaluator = StreamingEvaluator(registry=_null_registry())
    merged = 0
    skipped = 0
    for e in exports:
        # per-worker isolation: a mid-rollout fleet may mix model
        # versions whose sketch grids differ — that worker's export is
        # SKIPPED (and counted), never allowed to kill the whole merge.
        # Compatibility is checked BEFORE folding so a mismatch cannot
        # leave a partial (inexact) contribution behind.
        try:
            if "live" in e:
                if live is None:
                    live = DatasetProfile.from_state(e["live"])
                elif not _grids_compatible(live, e["live"]):
                    skipped += 1
                    continue
                else:
                    live.merge(e["live"])
            if "eval" in e:
                evaluator.merge_export(e["eval"])
            if reference is None and "reference" in e:
                reference = DatasetProfile.from_state(e["reference"])
            merged += 1
        except (KeyError, TypeError, ValueError):
            skipped += 1
    out = {"active": True, "workers": merged,
           "eval": evaluator.export()}
    if skipped:
        out["workers_skipped"] = skipped
    if reference is not None and live is not None:
        out["drift"] = drift_scores(reference, live)
        out["live"] = live.state()
    return out


class _NullRegistry:
    """Metric sink for merge-only evaluators: a fleet merge must not
    bump this process's own counters/gauges."""

    def inc(self, name, n=1):
        return 0

    def set_gauge(self, name, value):
        pass


_null = _NullRegistry()


def _null_registry() -> _NullRegistry:
    return _null


# ------------------------------------------------------- process-wide default
_monitor: Optional[QualityMonitor] = None
_monitor_lock = threading.Lock()


def get_monitor() -> QualityMonitor:
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = QualityMonitor()
        return _monitor


def reset_monitor() -> QualityMonitor:
    """Replace the process-default monitor (tests isolate scenarios)."""
    global _monitor
    with _monitor_lock:
        _monitor = QualityMonitor()
        return _monitor


def configure_quality(**kwargs) -> QualityMonitor:
    return get_monitor().configure(**kwargs)


def observe_serving(features, predictions, request_ids=None) -> int:
    """Hot-path entry (io/plan.py calls this per served batch): a cheap
    no-op until a reference profile is installed; never raises into the
    serving worker."""
    monitor = _monitor
    if monitor is None or not monitor.active:
        return 0
    try:
        return monitor.observe_serving(features, predictions, request_ids)
    except Exception:  # noqa: BLE001 - observability must not fail serving
        return 0


def record_label(request_id: str, label) -> str:
    return get_monitor().record_label(request_id, label)


def export_quality() -> dict:
    """JSON-safe export of the process monitor (flight bundles dump this
    as quality.json; {"active": False} until a reference exists). Never
    raises — a broken sketch loses the quality block, not the bundle."""
    monitor = _monitor
    if monitor is None or not monitor.active:
        return {"active": False}
    try:
        return monitor.export()
    except Exception:  # noqa: BLE001
        return {"active": False}


def refresh_quality_gauges(registry=None) -> dict:
    """Exposition hook: refresh drift gauges right before a scrape (the
    resource-gauge pattern). No-op until the monitor is active."""
    monitor = _monitor
    if monitor is None or not monitor.active:
        return {}
    try:
        return monitor.refresh_gauges(registry)
    except Exception:  # noqa: BLE001 - a scrape never fails on drift math
        return {}


def quality_http_response() -> tuple:
    """(status, payload, content_type) for GET /quality — the shared
    handler body every exposition surface mounts."""
    import json
    return 200, json.dumps(export_quality()).encode(), "application/json"


def quality_watch_rules(max_drift: float = 0.25,
                        min_metric: Optional[float] = None,
                        metric: str = "quality.eval.accuracy") -> list:
    """Watcher rules over the quality series: trip when the fleet's worst
    per-column PSI exceeds `max_drift`, and (optionally) when the online
    metric sinks under `min_metric` — feed to `TelemetryWatcher(rules=)`
    over a poller that retains the merged gauges."""
    from .watch import WatchRule
    rules = [WatchRule(key=tnames.QUALITY_DRIFT_MAX, max_value=max_drift,
                       min_samples=1)]
    if min_metric is not None:
        rules.append(WatchRule(key=metric, min_value=min_metric,
                               min_samples=1))
    return rules
