"""Windowed aggregation: time-sharded rings under every counter/histogram.

PR 5's telemetry is cumulative-since-process-start — `Histogram.snapshot()`
mixes the first request with the millionth, so a load spike five seconds
ago and a cold start five hours ago read identically. Decision-grade
signals (SLO burn rates, admission control, replica autoscaling — ROADMAP
items 3/4) need *recent* percentiles. This module gives every metric a
bounded windowed view without a second bookkeeping path at call sites:

- `WindowedHistogram` — a ring of per-interval bucket-count shards sharing
  the module-level geometric bounds of `reliability.metrics.Histogram`.
  The owning histogram forwards `(bucket_idx, ms)` from its own bisect,
  so the windowed view costs one extra list increment per observation.
  `state(window_s)` merges the shards covering the last N seconds into
  the standard mergeable histogram-state dict — percentiles are then
  recomputed from merged bucket counts (exactly the cross-worker merge
  discipline `scrape_cluster` already enforces), never averaged.
- `WindowedCounter` — the same ring over plain ints; `total(window_s)` is
  the count landed in the last N seconds (error-rate numerators and
  denominators for the SLO engine).

Sharding model: wall time is cut into fixed intervals; shard `k` covers
`[k*interval, (k+1)*interval)` and lives in ring slot `k % n`. Writing to
a slot whose recorded interval is stale resets it first — expiry is
O(1) amortized and needs no sweeper thread. A read over `window_s`
includes every shard whose interval overlaps `(now - window_s, now]`, so
the answer covers between `window_s` and `window_s + interval` of
history (standard ring-buffer windowing slack; the interval is the
resolution knob). Memory is `shards * buckets` ints per histogram —
bounded regardless of traffic, same contract as the cumulative buckets.

The clock is injectable (monotonic by default) so roll-off is testable
without wall-clock sleeps.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..reliability.metrics import histogram_bounds_ms

# bucket count of the shared geometric layout (bounds + one overflow)
_HIST_BUCKETS = len(histogram_bounds_ms()) + 1


class _Ring:
    """Slot bookkeeping shared by both windowed kinds: maps now -> the
    live slot (resetting stale ones) and enumerates the slots covering a
    lookback window. Callers hold their own lock around every use."""

    __slots__ = ("interval_s", "n", "_epochs", "_clock")

    def __init__(self, interval_s: float, shards: int,
                 clock: Callable[[], float]):
        if interval_s <= 0.0 or shards <= 1:
            raise ValueError("windowed ring needs interval_s > 0 and "
                             ">= 2 shards (one is always partial)")
        self.interval_s = float(interval_s)
        self.n = int(shards)
        # interval index currently stored in each slot; None = never used
        self._epochs: list = [None] * self.n
        self._clock = clock

    def slot(self) -> tuple:
        """(slot_index, is_stale): the slot for the current interval;
        is_stale means the caller must reset the slot's payload before
        writing (a previous interval's data still lives there)."""
        k = int(self._clock() // self.interval_s)
        i = k % self.n
        stale = self._epochs[i] != k
        if stale:
            self._epochs[i] = k
        return i, stale

    def live_slots(self, window_s: float) -> list:
        """Slot indices whose interval overlaps `(now - window_s, now]`.
        Shard k covers [k*iv, (k+1)*iv): it overlaps iff its end is past
        the window start and its start is not in the future."""
        now = self._clock()
        k_now = int(now // self.interval_s)
        k_min = int(max(now - float(window_s), 0.0) // self.interval_s)
        out = []
        for i, epoch in enumerate(self._epochs):
            if epoch is not None and k_min <= epoch <= k_now:
                out.append(i)
        return out

    @property
    def span_s(self) -> float:
        """Guaranteed lookback (the current shard is partial)."""
        return self.interval_s * (self.n - 1)


class WindowedHistogram:
    """Ring of per-interval histogram shards (counts + count/sum/min/max
    per shard), merged on read. Attached to a cumulative Histogram by
    `MetricsRegistry`; `observe_idx` reuses the owner's bucket bisect."""

    __slots__ = ("_ring", "_counts", "_count", "_sum_ms", "_min_ms",
                 "_max_ms", "_lock")

    def __init__(self, interval_s: float, shards: int,
                 clock: Callable[[], float] = time.monotonic):
        self._ring = _Ring(interval_s, shards, clock)
        n = self._ring.n
        self._counts = [[0] * _HIST_BUCKETS for _ in range(n)]
        self._count = [0] * n
        self._sum_ms = [0.0] * n
        self._min_ms = [float("inf")] * n
        self._max_ms = [0.0] * n
        self._lock = threading.Lock()

    def _reset_slot(self, i: int) -> None:
        counts = self._counts[i]
        for j in range(_HIST_BUCKETS):
            counts[j] = 0
        self._count[i] = 0
        self._sum_ms[i] = 0.0
        self._min_ms[i] = float("inf")
        self._max_ms[i] = 0.0

    def observe_idx(self, idx: int, ms: float) -> None:
        """One observation into the current shard; `idx` is the bucket
        index the owning Histogram already computed."""
        with self._lock:
            i, stale = self._ring.slot()
            if stale:
                self._reset_slot(i)
            self._counts[i][idx] += 1
            self._count[i] += 1
            self._sum_ms[i] += ms
            if ms < self._min_ms[i]:
                self._min_ms[i] = ms
            if ms > self._max_ms[i]:
                self._max_ms[i] = ms

    def state(self, window_s: float) -> dict:
        """Mergeable histogram-state dict (same shape as
        `Histogram.state()`) covering the shards of the last `window_s`
        seconds — elementwise bucket-count sums, so `merge_states` and
        `Histogram.from_state` consume it unchanged."""
        counts = [0] * _HIST_BUCKETS
        count = 0
        sum_ms = 0.0
        min_ms = float("inf")
        max_ms = 0.0
        with self._lock:
            for i in self._ring.live_slots(window_s):
                shard = self._counts[i]
                for j in range(_HIST_BUCKETS):
                    counts[j] += shard[j]
                count += self._count[i]
                sum_ms += self._sum_ms[i]
                if self._min_ms[i] < min_ms:
                    min_ms = self._min_ms[i]
                if self._max_ms[i] > max_ms:
                    max_ms = self._max_ms[i]
        return {"counts": counts, "count": count, "sum_ms": sum_ms,
                "min_ms": None if count == 0 else min_ms,
                "max_ms": max_ms}

    def snapshot(self, window_s: float, name: str = "window") -> dict:
        """snapshot()-shaped percentiles over the window, recomputed from
        the merged shard buckets."""
        from ..reliability.metrics import Histogram
        return Histogram.from_state(name, self.state(window_s)).snapshot()

    @property
    def span_s(self) -> float:
        return self._ring.span_s


class WindowedCounter:
    """Ring of per-interval increment totals; `total(window_s)` is the
    count from the last N seconds."""

    __slots__ = ("_ring", "_totals", "_lock")

    def __init__(self, interval_s: float, shards: int,
                 clock: Callable[[], float] = time.monotonic):
        self._ring = _Ring(interval_s, shards, clock)
        self._totals = [0] * self._ring.n
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            i, stale = self._ring.slot()
            if stale:
                self._totals[i] = 0
            self._totals[i] += n

    def total(self, window_s: float) -> int:
        with self._lock:
            return sum(self._totals[i]
                       for i in self._ring.live_slots(window_s))

    @property
    def span_s(self) -> float:
        return self._ring.span_s


def set_clock(metric, clock: Callable[[], float]) -> None:
    """Swap a windowed metric's clock (tests drive roll-off with a fake
    clock instead of sleeping). Existing shard epochs are kept — the fake
    clock should start at or after the real one's last reading, or start
    from a fresh metric."""
    window = getattr(metric, "window", metric)
    window._ring._clock = clock
