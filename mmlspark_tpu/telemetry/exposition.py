"""Cross-process metrics exposition: Prometheus text rendering, a
machine-mergeable JSON form, and a cluster-wide scrape helper.

Every process's `reliability.metrics.MetricsRegistry` is in-memory only;
this module gives it the two standard export surfaces a production serving
stack needs (PAPERS.md: production monitoring stacks):

- `render_prometheus(registry)` — the Prometheus text format (0.0.4).
  Counters render as `<name>_total`, gauges plain, wall-clock timings as a
  `_seconds_total` / `_calls_total` pair, and histograms with CUMULATIVE
  `_bucket{le="..."}` lines in SECONDS (the Prometheus unit convention;
  our buckets are stored in ms and divided out here). The original dotted
  metric name rides the `# HELP` line, so greps for `serving.request.e2e`
  find its exposition block.
- `/metrics` + `/metrics.json` are mounted on `ServingServer` (both
  transports) and `ServiceRegistry` via `metrics_http_response` — one
  implementation, three mounts.
- `scrape_cluster(registry_address)` — pulls `/metrics.json` from every
  worker registered in the `ServiceRegistry` and merges them EXACTLY:
  counters/timings sum, histogram bucket counts sum elementwise (all
  histograms share the module-level geometric bounds), and percentiles are
  recomputed from the merged buckets — never averaged across workers.
  Gauges are last-value signals with no cross-process meaning, so the
  merge keeps `max` (worst queue depth wins) — documented, not silent.
"""
from __future__ import annotations

import json
import re
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import NamedTuple, Optional

from ..reliability.metrics import (Histogram, MetricsRegistry,
                                   histogram_bounds_ms, reliability_metrics)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# exemplars are only legal in the OpenMetrics format — a 0.0.4 parser
# reads the trailing `# {...}` as a malformed timestamp and rejects the
# whole scrape — so /metrics?exemplars=1 switches format AND declares it
OPENMETRICS_CONTENT_TYPE = ("application/openmetrics-text; "
                            "version=1.0.0; charset=utf-8")

# windows rendered as Prometheus gauges on GET /metrics (seconds); the
# JSON form takes any ?window= the ring covers
PROM_WINDOWS_S = (60.0,)
_WINDOW_QUANTILES = ((50.0, "0.5"), (99.0, "0.99"), (99.9, "0.999"))

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Sanitize a dotted metric name to the Prometheus grammar."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else f"{v:.9g}"


def render_prometheus(registry=None, state: Optional[dict] = None,
                      windows: Optional[tuple] = None,
                      exemplars: bool = False) -> str:
    """Render a registry (default: the process-wide `reliability_metrics`)
    or a raw `export_state()` dict as Prometheus text. `windows` selects
    the lookbacks for the windowed quantile gauges (default
    `PROM_WINDOWS_S`; only a live registry carries shards to render).
    `exemplars=True` appends OpenMetrics exemplar suffixes to histogram
    bucket lines — the caller must then serve the output under
    `OPENMETRICS_CONTENT_TYPE` with an `# EOF` trailer, never as 0.0.4
    (which cannot carry them)."""
    if state is None:
        reg = registry if registry is not None else reliability_metrics
        state = reg.export_state()
    bounds = histogram_bounds_ms()
    lines: list = []
    # OpenMetrics (the exemplar mode) names the FAMILY without the
    # `_total` suffix while the counter sample keeps it; 0.0.4 metadata
    # names the sample itself. Strict OM parsers reject the 0.0.4
    # spelling as a name clash, so the suffix placement follows the
    # negotiated format.
    om = exemplars
    for name in sorted(state.get("counters", {})):
        pn = prom_name(name)
        family = pn if om else pn + "_total"
        lines.append(f"# HELP {family} {name}")
        lines.append(f"# TYPE {family} counter")
        lines.append(f"{pn}_total {_fmt(state['counters'][name])}")
    for name in sorted(state.get("timings", {})):
        total, count = state["timings"][name]
        pn = prom_name(name)
        sfx = "" if om else "_total"
        lines.append(f"# HELP {pn}_seconds{sfx} {name} (wall-clock sink)")
        lines.append(f"# TYPE {pn}_seconds{sfx} counter")
        lines.append(f"{pn}_seconds_total {_fmt(total)}")
        lines.append(f"# TYPE {pn}_calls{sfx} counter")
        lines.append(f"{pn}_calls_total {_fmt(count)}")
    for name in sorted(state.get("gauges", {})):
        pn = prom_name(name)
        lines.append(f"# HELP {pn} {name}")
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(state['gauges'][name])}")
    for name in sorted(state.get("hists", {})):
        h = state["hists"][name]
        pn = prom_name(name) + "_seconds"
        lines.append(f"# HELP {pn} {name} latency histogram")
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        counts = h["counts"]
        hist_ex = (h.get("exemplars") or {}) if exemplars else {}
        for i, bound_ms in enumerate(bounds):
            cum += counts[i]
            lines.append(f'{pn}_bucket{{le="{_fmt(bound_ms / 1000.0)}"}} '
                         f"{cum}" + _exemplar_suffix(hist_ex, i))
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h["count"]}'
                     + _exemplar_suffix(hist_ex, len(bounds)))
        lines.append(f"{pn}_sum {_fmt(h['sum_ms'] / 1000.0)}")
        lines.append(f"{pn}_count {h['count']}")
    if registry is not None or state is None:
        reg = registry if registry is not None else reliability_metrics
        lines.extend(_render_window_gauges(
            reg, windows if windows is not None else PROM_WINDOWS_S))
    return "\n".join(lines) + "\n"


def _exemplar_suffix(exemplars: dict, idx: int) -> str:
    """OpenMetrics exemplar for one bucket line: the last trace id that
    landed in this bucket, with its value (seconds) and wall timestamp —
    `... # {trace_id="<id>"} 0.093 1723450000.1`. Empty when the bucket
    has none (exemplars are per-observation opt-in)."""
    ex = exemplars.get(str(idx))
    if ex is None:
        ex = exemplars.get(idx)
    if not ex:
        return ""
    trace_id, ms, ts = ex[0], float(ex[1]), float(ex[2])
    # the timestamp gets millisecond precision, NOT _fmt's 9 significant
    # digits — current epoch seconds would collapse to 10 s resolution
    # in exponent form, useless for ordering requests in a burn window
    return (f' # {{trace_id="{trace_id}"}} {_fmt(ms / 1000.0)}'
            f" {ts:.3f}")


def _render_window_gauges(reg, windows) -> list:
    """Windowed quantile gauges next to the cumulative series: one gauge
    family per histogram, labeled by window and quantile (plus the
    windowed count so rates are readable). Only rendered from a LIVE
    registry — a raw state dict carries no shards."""
    lines: list = []
    for window_s in windows:
        state = reg.window_state(window_s)
        win = _fmt(state["window_s"])
        for name in sorted(state.get("hists", {})):
            h = Histogram.from_state(name, state["hists"][name])
            pn = prom_name(name)
            lines.append(f"# HELP {pn}_window_seconds {name} windowed "
                         f"quantiles (last {win}s, shard-merged)")
            lines.append(f"# TYPE {pn}_window_seconds gauge")
            for q, label in _WINDOW_QUANTILES:
                lines.append(
                    f'{pn}_window_seconds{{window="{win}",'
                    f'quantile="{label}"}} '
                    f"{_fmt(h.percentile(q) / 1000.0)}")
            lines.append(f"# TYPE {pn}_window_count gauge")
            lines.append(f'{pn}_window_count{{window="{win}"}} '
                         f"{h.count}")
    return lines


def _wants_exemplars(path: str) -> bool:
    """?exemplars=1 (or any value but 0/false) on /metrics."""
    query = path.partition("?")[2]
    values = urllib.parse.parse_qs(query).get("exemplars")
    return bool(values) and values[-1].lower() not in ("0", "", "false")


def _parse_window(path: str):
    """(base_path, window_s | None) from a request path; raises
    ValueError on a malformed window so callers 400 instead of silently
    serving cumulative numbers to an autoscaler that asked for recent."""
    base, _, query = path.partition("?")
    values = urllib.parse.parse_qs(query).get("window")
    if not values:
        return base, None
    window_s = float(values[-1])
    # `not (> 0)` rather than `<= 0`: NaN fails both comparisons and must
    # land in the 400, not raise deep inside the shard merge
    if not (window_s > 0.0):
        raise ValueError(f"window must be > 0, got {window_s}")
    return base, window_s


def metrics_http_response(path: str, registry=None) -> tuple:
    """(status, payload_bytes, content_type) for the exposition GETs —
    `/metrics`, `/metrics.json[?window=N]`, `/slo`, and `/debug/bundle`
    — the shared handler body `ServingServer` and `ServiceRegistry`
    mount."""
    reg = registry if registry is not None else reliability_metrics
    try:
        base, window_s = _parse_window(path)
    except ValueError as e:
        return 400, json.dumps({"error": str(e)}).encode(), \
            "application/json"
    if base in ("/slo", "/metrics", "/metrics.json"):
        # model-quality gauges refresh right before any read that could
        # consume them: the drift gauges a /slo quality objective reads
        # and a /metrics scrape ships must reflect the live sketches,
        # not the last scrape. Guarded — a broken sketch loses drift
        # gauges, never the scrape; a process with no quality monitor
        # pays one None check.
        try:
            from .quality import refresh_quality_gauges
            refresh_quality_gauges(reg)
        except Exception:  # noqa: BLE001
            pass
        # canary gauges refresh on the same cadence: the candidate-vs-
        # incumbent comparison a canary objective or watch rule reads
        # must reflect the splits as of THIS scrape. Same guard.
        try:
            from .lineage import refresh_canary_gauges
            refresh_canary_gauges(reg)
        except Exception:  # noqa: BLE001
            pass
    if base == "/quality":
        from .quality import quality_http_response
        return quality_http_response()
    if base == "/versions":
        from .lineage import versions_http_response
        return versions_http_response(window_s=window_s)
    if base == "/slo":
        from .slo import get_engine
        return 200, json.dumps(get_engine().verdict()).encode(), \
            "application/json"
    if base == "/debug/bundle":
        return _bundle_response()
    if base == "/debug/profile":
        return _profile_response(path)
    # every metrics scrape carries a FRESH memory sample: device
    # memory_stats + host RSS land in gauges right before export, so the
    # fleet's headroom rides next to its latency (telemetry/perf.py;
    # guarded — a broken backend loses gauges, never the scrape)
    try:
        from .perf import sample_resource_gauges
        sample_resource_gauges(reg)
    except Exception:  # noqa: BLE001
        pass
    if base == "/metrics.json":
        return 200, \
            json.dumps(reg.export_state(window_s=window_s)).encode(), \
            "application/json"
    # /metrics honors ?window= too: it selects the windowed-gauge
    # lookback (the cumulative series are part of the Prometheus
    # contract and always render). ?exemplars=1 switches the response to
    # OpenMetrics (exemplar suffixes + # EOF trailer + its content
    # type); the default stays clean 0.0.4 so a stock Prometheus scrape
    # never sees a token it cannot parse.
    windows = (window_s,) if window_s is not None else None
    if _wants_exemplars(path):
        text = render_prometheus(reg, windows=windows, exemplars=True)
        return 200, (text + "# EOF\n").encode(), OPENMETRICS_CONTENT_TYPE
    return 200, render_prometheus(reg, windows=windows).encode(), \
        PROM_CONTENT_TYPE


def _bundle_response() -> tuple:
    """GET /debug/bundle: dump a flight-recorder bundle on demand. 503
    when no bundle dir is configured, 429 when the rate limit suppressed
    the dump (a scrape loop must not turn the debug endpoint into a disk
    filler), else the bundle manifest."""
    from .perf import get_flight_recorder
    rec = get_flight_recorder()
    if not rec.enabled:
        return 503, json.dumps(
            {"error": "flight recorder disabled — set "
                      "MMLSPARK_TPU_BUNDLE_DIR or "
                      "telemetry.perf.configure_flight_recorder("
                      "bundle_dir=...)"}).encode(), "application/json"
    try:
        manifest = rec.dump("on-demand")
    except Exception as e:  # noqa: BLE001 - a 500 beats a dropped scrape
        return 500, json.dumps(
            {"error": f"bundle write failed: {e}"}).encode(), \
            "application/json"
    if manifest is None:
        return 429, json.dumps(
            {"error": "bundle suppressed by rate limit",
             "min_interval_s": rec.min_interval_s}).encode(), \
            "application/json"
    return 200, json.dumps(manifest).encode(), "application/json"


def _profile_response(path: str) -> tuple:
    """GET /debug/profile?ms=N: capture a device profile for N ms and
    answer the parsed manifest (per-op table + region rollup). Same
    contract as /debug/bundle: 503 when no profile dir is configured,
    429 when the rate limit suppressed the capture, 500 (with the slot
    rolled back and the partial dir removed) on a failed capture; a
    malformed ms answers 400. The capture blocks the handler for N ms —
    ms is clamped to the session's max_ms, and the rate limit keeps a
    scrape loop from turning the endpoint into a profiler DoS."""
    from .profiler import get_profile_session
    query = path.partition("?")[2]
    values = urllib.parse.parse_qs(query).get("ms")
    ms = None
    if values:
        try:
            ms = float(values[-1])
        except ValueError:
            ms = float("nan")
        if not (ms > 0.0):   # NaN fails too -> 400, like ?window=
            return 400, json.dumps(
                {"error": f"ms must be > 0, got {values[-1]!r}"}).encode(), \
                "application/json"
    session = get_profile_session()
    if not session.enabled:
        return 503, json.dumps(
            {"error": "profiling disabled — set MMLSPARK_TPU_PROFILE_DIR "
                      "or telemetry.profiler.configure_profile_session("
                      "profile_dir=...)"}).encode(), "application/json"
    try:
        manifest = session.capture(ms=ms, reason="on-demand")
    except Exception as e:  # noqa: BLE001 - a 500 beats a dropped scrape
        return 500, json.dumps(
            {"error": f"profile capture failed: {e}"}).encode(), \
            "application/json"
    if manifest is None:
        return 429, json.dumps(
            {"error": "profile suppressed by rate limit",
             "min_interval_s": session.min_interval_s}).encode(), \
            "application/json"
    return 200, json.dumps(manifest).encode(), "application/json"


# ------------------------------------------------- trainer scrape surface
class _ExpositionHandler(BaseHTTPRequestHandler):
    server_version = "mmlspark_tpu-exposition/1.0"

    def _answer(self):
        # EXPOSITION_PATHS is owned by io/serving (the serving ingress
        # mounts the same handler body); imported lazily to keep this
        # module importable below the io layer
        from ..io.serving import EXPOSITION_PATHS
        if self.path.split("?", 1)[0] not in EXPOSITION_PATHS:
            status, ctype = 404, "application/json"
            payload = b'{"error": "not found"}'
        else:
            status, payload, ctype = metrics_http_response(
                self.path, registry=self.server.exposition_registry)
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802
        self._answer()

    def do_POST(self):  # noqa: N802 - pollers that POST still get answered
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length > 0:
            self.rfile.read(length)
        self._answer()

    def log_message(self, *args):  # quiet
        pass


class _ExpositionHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    request_queue_size = 32


class ExpositionServer:
    """The trainer-side scrape surface: a lightweight HTTP server that
    answers ONLY the exposition paths (`/metrics`, `/metrics.json`,
    `/slo`, `/debug/bundle`) — the same handler body `ServingServer` and
    `ServiceRegistry` mount, without a serving queue behind it. A
    training process mounts one so `scrape_cluster`/`TelemetryPoller`
    can pull its goodput/MFU gauges and step histograms next to the
    serving fleet's latency (see `expose_trainer` for the registered
    one-liner)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 registry=None):
        self._httpd = _ExpositionHTTPServer((host, port),
                                            _ExpositionHandler)
        self._httpd.exposition_registry = registry  # type: ignore
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="trainer-exposition")

    def start(self) -> "ExpositionServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"


def expose_trainer(host: str = "127.0.0.1", port: int = 0,
                   registry_address: Optional[str] = None,
                   name: str = "trainer", process_id: Optional[int] = None,
                   goodput_floor: Optional[float] = 0.9,
                   registry=None) -> ExpositionServer:
    """Mount the trainer scrape surface and (optionally) register it.

    - Starts an `ExpositionServer` on (host, port).
    - With `registry_address`, reports it to the `ServiceRegistry` with
      ``kind="trainer"`` so `scrape_cluster(kind=...)` and the poller can
      target trainers without probing.
    - With `goodput_floor` set (default 0.9), appends the goodput-floor
      `Objective` to the process SLO engine — `/slo` on this endpoint
      then burns when goodput sinks below the floor, and the flight
      recorder dumps a bundle (with the step-phase breakdown in
      goodput.json) on the transition.
    """
    server = ExpositionServer(host=host, port=port,
                              registry=registry).start()
    if goodput_floor is not None:
        from .slo import get_engine, trainer_objectives
        engine = get_engine()
        have = {o.name for o in engine.objectives}
        for obj in trainer_objectives(goodput_floor=goodput_floor):
            if obj.name not in have:
                engine.objectives.append(obj)
    if registry_address:
        from ..io.registry import report_server_to_registry
        if process_id is None:
            import sys
            process_id = 0
            if "jax" in sys.modules:
                try:
                    import jax
                    process_id = jax.process_index()
                except Exception:  # noqa: BLE001 - no backend: leader
                    process_id = 0
        report_server_to_registry(registry_address, name, host, server.port,
                                  process_id=process_id, num_partitions=0,
                                  kind="trainer")
    return server


# ---------------------------------------------------------------- merging
def merge_states(states: list) -> dict:
    """Merge raw `export_state()` dicts: counters/timings sum, histogram
    buckets sum elementwise, gauges keep max (see module docstring)."""
    merged = {"counters": {}, "timings": {}, "gauges": {}, "hists": {}}
    windows = [st["window_s"] for st in states if "window_s" in st]
    if windows:
        # a merged windowed state keeps the NARROWEST effective window —
        # the honest label when rings were configured unevenly
        merged["window_s"] = min(windows)
    for st in states:
        for name, v in st.get("counters", {}).items():
            merged["counters"][name] = merged["counters"].get(name, 0) + v
        for name, (total, count) in st.get("timings", {}).items():
            t = merged["timings"].setdefault(name, [0.0, 0])
            t[0] += total
            t[1] += count
        for name, v in st.get("gauges", {}).items():
            prev = merged["gauges"].get(name)
            merged["gauges"][name] = v if prev is None else max(prev, v)
        for name, h in st.get("hists", {}).items():
            m = merged["hists"].get(name)
            if m is None:
                merged["hists"][name] = m = {
                    "counts": list(h["counts"]), "count": h["count"],
                    "sum_ms": h["sum_ms"], "min_ms": h.get("min_ms"),
                    "max_ms": h.get("max_ms", 0.0)}
                ex = h.get("exemplars")
                if ex:
                    m["exemplars"] = dict(ex)
                continue
            m["counts"] = [a + b for a, b in zip(m["counts"], h["counts"])]
            m["count"] += h["count"]
            m["sum_ms"] += h["sum_ms"]
            mins = [x for x in (m.get("min_ms"), h.get("min_ms"))
                    if x is not None]
            m["min_ms"] = min(mins) if mins else None
            m["max_ms"] = max(m.get("max_ms", 0.0), h.get("max_ms", 0.0))
            for idx, ex in (h.get("exemplars") or {}).items():
                # newest exemplar per bucket wins across workers (an
                # exemplar is a pointer, not a statistic — no sum/avg
                # has meaning; recency keeps it actionable)
                dst = m.setdefault("exemplars", {})
                prev = dst.get(idx)
                if prev is None or float(ex[2]) >= float(prev[2]):
                    dst[idx] = list(ex)
    return merged


def state_snapshot(state: dict) -> dict:
    """Flatten a raw state into the same key shape
    `MetricsRegistry.snapshot()` produces — histogram percentiles are
    recomputed from the (possibly merged) bucket counts."""
    out = dict(state.get("counters", {}))
    for label, (total, count) in state.get("timings", {}).items():
        out[f"{label}.seconds"] = total
        out[f"{label}.count"] = count
    out.update(state.get("gauges", {}))
    for name, h in state.get("hists", {}).items():
        for k, v in Histogram.from_state(name, h).snapshot().items():
            out[f"{name}.{k}"] = v
    return out


class ClusterSnapshot(NamedTuple):
    """`scrape_cluster` result: the exactly-merged flat snapshot plus each
    worker's raw state for per-host drill-down. `slo` is the fleet-merged
    `/slo` verdict when the scrape asked for it (None otherwise);
    `quality` is the fleet-merged `/quality` export (sketch counts
    summed, drift recomputed from the merged counts) when
    ``quality=True`` was passed; `versions` is the fleet-merged
    `/versions` export (per-version splits summed, `current_by_worker`
    naming which worker serves which ModelVersion — the rollout-skew
    record) when ``versions=True`` was passed."""
    merged: dict
    workers: list   # [(ServiceInfo, raw state dict), ...]
    slo: Optional[dict] = None
    quality: Optional[dict] = None
    versions: Optional[dict] = None


def scrape_cluster(registry_address: str, name: Optional[str] = None,
                   timeout: float = 10.0,
                   skip_unreachable: bool = True,
                   window: Optional[float] = None,
                   slo: bool = False,
                   kind: Optional[str] = None,
                   quality: bool = False,
                   versions: bool = False) -> ClusterSnapshot:
    """Pull `/metrics.json` from every worker the `ServiceRegistry` at
    `registry_address` knows (optionally one service `name`) and merge.
    A worker that died between registering and the scrape is skipped (its
    numbers are gone either way); pass `skip_unreachable=False` to raise
    instead.

    `window` scrapes `/metrics.json?window=N` — the merged snapshot then
    covers only each worker's last N seconds (bucket counts still sum
    elementwise; percentiles recompute from the merged windowed buckets).
    `slo=True` also pulls each worker's `/slo` verdict and merges them
    with `telemetry.slo.merge_verdicts` (counts sum, burns recompute).
    `quality=True` also pulls each worker's `/quality` export and merges
    them with `telemetry.quality.merge_quality_exports` — live sketch
    counts sum exactly, fleet drift recomputes from the merged counts
    (never averaged from per-worker scores). `versions=True` also pulls
    each worker's `/versions` export and merges it with
    `telemetry.lineage.merge_version_exports` — per-version metric
    splits sum exactly, and the result's `current_by_worker` map records
    which worker serves which ModelVersion (the rollout-skew signal the
    poller tracks); when combined with `slo=True`, per-worker verdicts
    also group into `versions["slo_by_version"]` by each worker's
    registered ServiceInfo.version, so a fleet SLO merge can be split by
    model version. `kind` scrapes only services of that registry kind
    (``"serving"`` / ``"trainer"``) — no probing; the default merges
    both, which is well-defined because trainer gauges (goodput) keep
    max and step histograms bucket-sum exactly like every other
    metric."""
    from ..io.registry import ServiceInfo, list_services
    if name is not None:
        infos = list_services(registry_address, name, timeout=timeout)
    else:
        with urllib.request.urlopen(registry_address + "/services",
                                    timeout=timeout) as resp:
            infos = [ServiceInfo(**d) for d in json.loads(resp.read())]
    if kind is not None:
        infos = [i for i in infos
                 if getattr(i, "kind", "serving") == kind]
    metrics_path = "/metrics.json"
    if window is not None:
        metrics_path += f"?window={float(window):g}"
    workers = []
    slo_verdicts = []
    quality_exports = []
    version_exports = []
    for info in infos:
        try:
            with urllib.request.urlopen(info.address + metrics_path,
                                        timeout=timeout) as resp:
                state = json.loads(resp.read())
            if slo:
                with urllib.request.urlopen(info.address + "/slo",
                                            timeout=timeout) as resp:
                    slo_verdicts.append((info, json.loads(resp.read())))
            if quality:
                # isolated: a worker without /quality (a pre-quality
                # version mid-rollout) keeps its metrics and SLO in the
                # merge — it just contributes no quality export
                try:
                    with urllib.request.urlopen(info.address + "/quality",
                                                timeout=timeout) as resp:
                        quality_exports.append(json.loads(resp.read()))
                except (OSError, ValueError):
                    pass
            if versions:
                # same isolation as /quality: a pre-versions worker
                # still merges its metrics/SLO
                try:
                    with urllib.request.urlopen(info.address + "/versions",
                                                timeout=timeout) as resp:
                        # keyed by address: unique per worker even when
                        # every partition registers the same service name
                        version_exports.append(
                            (info.address, json.loads(resp.read())))
                except (OSError, ValueError):
                    pass
            workers.append((info, state))
        except (OSError, ValueError) as e:
            if not skip_unreachable:
                raise RuntimeError(
                    f"scrape of {info.address} failed: {e}") from e
    merged_state = merge_states([st for _, st in workers])
    merged = state_snapshot(merged_state)
    merged["telemetry.scrape.workers"] = len(workers)
    if "window_s" in merged_state:
        merged["telemetry.scrape.window_s"] = merged_state["window_s"]
    merged_slo = None
    if slo:
        from .slo import merge_verdicts
        merged_slo = merge_verdicts([v for _, v in slo_verdicts])
    merged_quality = None
    if quality:
        from .quality import merge_quality_exports
        try:
            merged_quality = merge_quality_exports(quality_exports)
        except Exception:  # noqa: BLE001 - the metrics/SLO merge stands
            merged_quality = None
    merged_versions = None
    if versions:
        from .lineage import merge_version_exports
        try:
            merged_versions = merge_version_exports(version_exports)
        except Exception:  # noqa: BLE001 - the metrics/SLO merge stands
            merged_versions = None
        if merged_versions is not None and slo:
            # fleet SLO split by version: group per-worker verdicts by
            # each worker's REGISTERED version (ServiceInfo.version) and
            # merge each group exactly — a canary worker's burn no
            # longer hides inside the fleet-wide verdict
            from .slo import merge_verdicts as _mv
            groups: dict = {}
            for info, verdict in slo_verdicts:
                ver = getattr(info, "version", None)
                if ver is not None:
                    groups.setdefault(ver, []).append(verdict)
            if groups:
                merged_versions["slo_by_version"] = {
                    ver: _mv(vs) for ver, vs in groups.items()}
    return ClusterSnapshot(merged=merged, workers=workers, slo=merged_slo,
                           quality=merged_quality,
                           versions=merged_versions)
