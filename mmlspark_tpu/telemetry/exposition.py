"""Cross-process metrics exposition: Prometheus text rendering, a
machine-mergeable JSON form, and a cluster-wide scrape helper.

Every process's `reliability.metrics.MetricsRegistry` is in-memory only;
this module gives it the two standard export surfaces a production serving
stack needs (PAPERS.md: production monitoring stacks):

- `render_prometheus(registry)` — the Prometheus text format (0.0.4).
  Counters render as `<name>_total`, gauges plain, wall-clock timings as a
  `_seconds_total` / `_calls_total` pair, and histograms with CUMULATIVE
  `_bucket{le="..."}` lines in SECONDS (the Prometheus unit convention;
  our buckets are stored in ms and divided out here). The original dotted
  metric name rides the `# HELP` line, so greps for `serving.request.e2e`
  find its exposition block.
- `/metrics` + `/metrics.json` are mounted on `ServingServer` (both
  transports) and `ServiceRegistry` via `metrics_http_response` — one
  implementation, three mounts.
- `scrape_cluster(registry_address)` — pulls `/metrics.json` from every
  worker registered in the `ServiceRegistry` and merges them EXACTLY:
  counters/timings sum, histogram bucket counts sum elementwise (all
  histograms share the module-level geometric bounds), and percentiles are
  recomputed from the merged buckets — never averaged across workers.
  Gauges are last-value signals with no cross-process meaning, so the
  merge keeps `max` (worst queue depth wins) — documented, not silent.
"""
from __future__ import annotations

import json
import re
import urllib.parse
import urllib.request
from typing import NamedTuple, Optional

from ..reliability.metrics import (Histogram, MetricsRegistry,
                                   histogram_bounds_ms, reliability_metrics)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# windows rendered as Prometheus gauges on GET /metrics (seconds); the
# JSON form takes any ?window= the ring covers
PROM_WINDOWS_S = (60.0,)
_WINDOW_QUANTILES = ((50.0, "0.5"), (99.0, "0.99"), (99.9, "0.999"))

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Sanitize a dotted metric name to the Prometheus grammar."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else f"{v:.9g}"


def render_prometheus(registry=None, state: Optional[dict] = None,
                      windows: Optional[tuple] = None) -> str:
    """Render a registry (default: the process-wide `reliability_metrics`)
    or a raw `export_state()` dict as Prometheus text. `windows` selects
    the lookbacks for the windowed quantile gauges (default
    `PROM_WINDOWS_S`; only a live registry carries shards to render)."""
    if state is None:
        reg = registry if registry is not None else reliability_metrics
        state = reg.export_state()
    bounds = histogram_bounds_ms()
    lines: list = []
    for name in sorted(state.get("counters", {})):
        pn = prom_name(name) + "_total"
        lines.append(f"# HELP {pn} {name}")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(state['counters'][name])}")
    for name in sorted(state.get("timings", {})):
        total, count = state["timings"][name]
        pn = prom_name(name)
        lines.append(f"# HELP {pn}_seconds_total {name} (wall-clock sink)")
        lines.append(f"# TYPE {pn}_seconds_total counter")
        lines.append(f"{pn}_seconds_total {_fmt(total)}")
        lines.append(f"# TYPE {pn}_calls_total counter")
        lines.append(f"{pn}_calls_total {_fmt(count)}")
    for name in sorted(state.get("gauges", {})):
        pn = prom_name(name)
        lines.append(f"# HELP {pn} {name}")
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(state['gauges'][name])}")
    for name in sorted(state.get("hists", {})):
        h = state["hists"][name]
        pn = prom_name(name) + "_seconds"
        lines.append(f"# HELP {pn} {name} latency histogram")
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        counts = h["counts"]
        for i, bound_ms in enumerate(bounds):
            cum += counts[i]
            lines.append(f'{pn}_bucket{{le="{_fmt(bound_ms / 1000.0)}"}} '
                         f"{cum}")
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{pn}_sum {_fmt(h['sum_ms'] / 1000.0)}")
        lines.append(f"{pn}_count {h['count']}")
    if registry is not None or state is None:
        reg = registry if registry is not None else reliability_metrics
        lines.extend(_render_window_gauges(
            reg, windows if windows is not None else PROM_WINDOWS_S))
    return "\n".join(lines) + "\n"


def _render_window_gauges(reg, windows) -> list:
    """Windowed quantile gauges next to the cumulative series: one gauge
    family per histogram, labeled by window and quantile (plus the
    windowed count so rates are readable). Only rendered from a LIVE
    registry — a raw state dict carries no shards."""
    lines: list = []
    for window_s in windows:
        state = reg.window_state(window_s)
        win = _fmt(state["window_s"])
        for name in sorted(state.get("hists", {})):
            h = Histogram.from_state(name, state["hists"][name])
            pn = prom_name(name)
            lines.append(f"# HELP {pn}_window_seconds {name} windowed "
                         f"quantiles (last {win}s, shard-merged)")
            lines.append(f"# TYPE {pn}_window_seconds gauge")
            for q, label in _WINDOW_QUANTILES:
                lines.append(
                    f'{pn}_window_seconds{{window="{win}",'
                    f'quantile="{label}"}} '
                    f"{_fmt(h.percentile(q) / 1000.0)}")
            lines.append(f'{pn}_window_count{{window="{win}"}} '
                         f"{h.count}")
    return lines


def _parse_window(path: str):
    """(base_path, window_s | None) from a request path; raises
    ValueError on a malformed window so callers 400 instead of silently
    serving cumulative numbers to an autoscaler that asked for recent."""
    base, _, query = path.partition("?")
    values = urllib.parse.parse_qs(query).get("window")
    if not values:
        return base, None
    window_s = float(values[-1])
    # `not (> 0)` rather than `<= 0`: NaN fails both comparisons and must
    # land in the 400, not raise deep inside the shard merge
    if not (window_s > 0.0):
        raise ValueError(f"window must be > 0, got {window_s}")
    return base, window_s


def metrics_http_response(path: str, registry=None) -> tuple:
    """(status, payload_bytes, content_type) for the exposition GETs —
    `/metrics`, `/metrics.json[?window=N]`, and `/slo` — the shared
    handler body `ServingServer` and `ServiceRegistry` mount."""
    reg = registry if registry is not None else reliability_metrics
    try:
        base, window_s = _parse_window(path)
    except ValueError as e:
        return 400, json.dumps({"error": str(e)}).encode(), \
            "application/json"
    if base == "/slo":
        from .slo import get_engine
        return 200, json.dumps(get_engine().verdict()).encode(), \
            "application/json"
    if base == "/metrics.json":
        return 200, \
            json.dumps(reg.export_state(window_s=window_s)).encode(), \
            "application/json"
    # /metrics honors ?window= too: it selects the windowed-gauge
    # lookback (the cumulative series are part of the Prometheus
    # contract and always render)
    windows = (window_s,) if window_s is not None else None
    return 200, render_prometheus(reg, windows=windows).encode(), \
        PROM_CONTENT_TYPE


# ---------------------------------------------------------------- merging
def merge_states(states: list) -> dict:
    """Merge raw `export_state()` dicts: counters/timings sum, histogram
    buckets sum elementwise, gauges keep max (see module docstring)."""
    merged = {"counters": {}, "timings": {}, "gauges": {}, "hists": {}}
    windows = [st["window_s"] for st in states if "window_s" in st]
    if windows:
        # a merged windowed state keeps the NARROWEST effective window —
        # the honest label when rings were configured unevenly
        merged["window_s"] = min(windows)
    for st in states:
        for name, v in st.get("counters", {}).items():
            merged["counters"][name] = merged["counters"].get(name, 0) + v
        for name, (total, count) in st.get("timings", {}).items():
            t = merged["timings"].setdefault(name, [0.0, 0])
            t[0] += total
            t[1] += count
        for name, v in st.get("gauges", {}).items():
            prev = merged["gauges"].get(name)
            merged["gauges"][name] = v if prev is None else max(prev, v)
        for name, h in st.get("hists", {}).items():
            m = merged["hists"].get(name)
            if m is None:
                merged["hists"][name] = {
                    "counts": list(h["counts"]), "count": h["count"],
                    "sum_ms": h["sum_ms"], "min_ms": h.get("min_ms"),
                    "max_ms": h.get("max_ms", 0.0)}
                continue
            m["counts"] = [a + b for a, b in zip(m["counts"], h["counts"])]
            m["count"] += h["count"]
            m["sum_ms"] += h["sum_ms"]
            mins = [x for x in (m.get("min_ms"), h.get("min_ms"))
                    if x is not None]
            m["min_ms"] = min(mins) if mins else None
            m["max_ms"] = max(m.get("max_ms", 0.0), h.get("max_ms", 0.0))
    return merged


def state_snapshot(state: dict) -> dict:
    """Flatten a raw state into the same key shape
    `MetricsRegistry.snapshot()` produces — histogram percentiles are
    recomputed from the (possibly merged) bucket counts."""
    out = dict(state.get("counters", {}))
    for label, (total, count) in state.get("timings", {}).items():
        out[f"{label}.seconds"] = total
        out[f"{label}.count"] = count
    out.update(state.get("gauges", {}))
    for name, h in state.get("hists", {}).items():
        for k, v in Histogram.from_state(name, h).snapshot().items():
            out[f"{name}.{k}"] = v
    return out


class ClusterSnapshot(NamedTuple):
    """`scrape_cluster` result: the exactly-merged flat snapshot plus each
    worker's raw state for per-host drill-down. `slo` is the fleet-merged
    `/slo` verdict when the scrape asked for it (None otherwise)."""
    merged: dict
    workers: list   # [(ServiceInfo, raw state dict), ...]
    slo: Optional[dict] = None


def scrape_cluster(registry_address: str, name: Optional[str] = None,
                   timeout: float = 10.0,
                   skip_unreachable: bool = True,
                   window: Optional[float] = None,
                   slo: bool = False) -> ClusterSnapshot:
    """Pull `/metrics.json` from every worker the `ServiceRegistry` at
    `registry_address` knows (optionally one service `name`) and merge.
    A worker that died between registering and the scrape is skipped (its
    numbers are gone either way); pass `skip_unreachable=False` to raise
    instead.

    `window` scrapes `/metrics.json?window=N` — the merged snapshot then
    covers only each worker's last N seconds (bucket counts still sum
    elementwise; percentiles recompute from the merged windowed buckets).
    `slo=True` also pulls each worker's `/slo` verdict and merges them
    with `telemetry.slo.merge_verdicts` (counts sum, burns recompute)."""
    from ..io.registry import ServiceInfo, list_services
    if name is not None:
        infos = list_services(registry_address, name, timeout=timeout)
    else:
        with urllib.request.urlopen(registry_address + "/services",
                                    timeout=timeout) as resp:
            infos = [ServiceInfo(**d) for d in json.loads(resp.read())]
    metrics_path = "/metrics.json"
    if window is not None:
        metrics_path += f"?window={float(window):g}"
    workers = []
    slo_verdicts = []
    for info in infos:
        try:
            with urllib.request.urlopen(info.address + metrics_path,
                                        timeout=timeout) as resp:
                state = json.loads(resp.read())
            if slo:
                with urllib.request.urlopen(info.address + "/slo",
                                            timeout=timeout) as resp:
                    slo_verdicts.append(json.loads(resp.read()))
            workers.append((info, state))
        except (OSError, ValueError) as e:
            if not skip_unreachable:
                raise RuntimeError(
                    f"scrape of {info.address} failed: {e}") from e
    merged_state = merge_states([st for _, st in workers])
    merged = state_snapshot(merged_state)
    merged["telemetry.scrape.workers"] = len(workers)
    if "window_s" in merged_state:
        merged["telemetry.scrape.window_s"] = merged_state["window_s"]
    merged_slo = None
    if slo:
        from .slo import merge_verdicts
        merged_slo = merge_verdicts(slo_verdicts)
    return ClusterSnapshot(merged=merged, workers=workers, slo=merged_slo)
