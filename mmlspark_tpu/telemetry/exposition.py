"""Cross-process metrics exposition: Prometheus text rendering, a
machine-mergeable JSON form, and a cluster-wide scrape helper.

Every process's `reliability.metrics.MetricsRegistry` is in-memory only;
this module gives it the two standard export surfaces a production serving
stack needs (PAPERS.md: production monitoring stacks):

- `render_prometheus(registry)` — the Prometheus text format (0.0.4).
  Counters render as `<name>_total`, gauges plain, wall-clock timings as a
  `_seconds_total` / `_calls_total` pair, and histograms with CUMULATIVE
  `_bucket{le="..."}` lines in SECONDS (the Prometheus unit convention;
  our buckets are stored in ms and divided out here). The original dotted
  metric name rides the `# HELP` line, so greps for `serving.request.e2e`
  find its exposition block.
- `/metrics` + `/metrics.json` are mounted on `ServingServer` (both
  transports) and `ServiceRegistry` via `metrics_http_response` — one
  implementation, three mounts.
- `scrape_cluster(registry_address)` — pulls `/metrics.json` from every
  worker registered in the `ServiceRegistry` and merges them EXACTLY:
  counters/timings sum, histogram bucket counts sum elementwise (all
  histograms share the module-level geometric bounds), and percentiles are
  recomputed from the merged buckets — never averaged across workers.
  Gauges are last-value signals with no cross-process meaning, so the
  merge keeps `max` (worst queue depth wins) — documented, not silent.
"""
from __future__ import annotations

import json
import re
import urllib.request
from typing import NamedTuple, Optional

from ..reliability.metrics import (Histogram, MetricsRegistry,
                                   histogram_bounds_ms, reliability_metrics)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Sanitize a dotted metric name to the Prometheus grammar."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else f"{v:.9g}"


def render_prometheus(registry=None, state: Optional[dict] = None) -> str:
    """Render a registry (default: the process-wide `reliability_metrics`)
    or a raw `export_state()` dict as Prometheus text."""
    if state is None:
        reg = registry if registry is not None else reliability_metrics
        state = reg.export_state()
    bounds = histogram_bounds_ms()
    lines: list = []
    for name in sorted(state.get("counters", {})):
        pn = prom_name(name) + "_total"
        lines.append(f"# HELP {pn} {name}")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt(state['counters'][name])}")
    for name in sorted(state.get("timings", {})):
        total, count = state["timings"][name]
        pn = prom_name(name)
        lines.append(f"# HELP {pn}_seconds_total {name} (wall-clock sink)")
        lines.append(f"# TYPE {pn}_seconds_total counter")
        lines.append(f"{pn}_seconds_total {_fmt(total)}")
        lines.append(f"# TYPE {pn}_calls_total counter")
        lines.append(f"{pn}_calls_total {_fmt(count)}")
    for name in sorted(state.get("gauges", {})):
        pn = prom_name(name)
        lines.append(f"# HELP {pn} {name}")
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt(state['gauges'][name])}")
    for name in sorted(state.get("hists", {})):
        h = state["hists"][name]
        pn = prom_name(name) + "_seconds"
        lines.append(f"# HELP {pn} {name} latency histogram")
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        counts = h["counts"]
        for i, bound_ms in enumerate(bounds):
            cum += counts[i]
            lines.append(f'{pn}_bucket{{le="{_fmt(bound_ms / 1000.0)}"}} '
                         f"{cum}")
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{pn}_sum {_fmt(h['sum_ms'] / 1000.0)}")
        lines.append(f"{pn}_count {h['count']}")
    return "\n".join(lines) + "\n"


def metrics_http_response(path: str, registry=None) -> tuple:
    """(status, payload_bytes, content_type) for a `/metrics[.json]` GET —
    the shared handler body `ServingServer` and `ServiceRegistry` mount."""
    reg = registry if registry is not None else reliability_metrics
    if path.startswith("/metrics.json"):
        return 200, json.dumps(reg.export_state()).encode(), \
            "application/json"
    return 200, render_prometheus(reg).encode(), PROM_CONTENT_TYPE


# ---------------------------------------------------------------- merging
def merge_states(states: list) -> dict:
    """Merge raw `export_state()` dicts: counters/timings sum, histogram
    buckets sum elementwise, gauges keep max (see module docstring)."""
    merged = {"counters": {}, "timings": {}, "gauges": {}, "hists": {}}
    for st in states:
        for name, v in st.get("counters", {}).items():
            merged["counters"][name] = merged["counters"].get(name, 0) + v
        for name, (total, count) in st.get("timings", {}).items():
            t = merged["timings"].setdefault(name, [0.0, 0])
            t[0] += total
            t[1] += count
        for name, v in st.get("gauges", {}).items():
            prev = merged["gauges"].get(name)
            merged["gauges"][name] = v if prev is None else max(prev, v)
        for name, h in st.get("hists", {}).items():
            m = merged["hists"].get(name)
            if m is None:
                merged["hists"][name] = {
                    "counts": list(h["counts"]), "count": h["count"],
                    "sum_ms": h["sum_ms"], "min_ms": h.get("min_ms"),
                    "max_ms": h.get("max_ms", 0.0)}
                continue
            m["counts"] = [a + b for a, b in zip(m["counts"], h["counts"])]
            m["count"] += h["count"]
            m["sum_ms"] += h["sum_ms"]
            mins = [x for x in (m.get("min_ms"), h.get("min_ms"))
                    if x is not None]
            m["min_ms"] = min(mins) if mins else None
            m["max_ms"] = max(m.get("max_ms", 0.0), h.get("max_ms", 0.0))
    return merged


def state_snapshot(state: dict) -> dict:
    """Flatten a raw state into the same key shape
    `MetricsRegistry.snapshot()` produces — histogram percentiles are
    recomputed from the (possibly merged) bucket counts."""
    out = dict(state.get("counters", {}))
    for label, (total, count) in state.get("timings", {}).items():
        out[f"{label}.seconds"] = total
        out[f"{label}.count"] = count
    out.update(state.get("gauges", {}))
    for name, h in state.get("hists", {}).items():
        for k, v in Histogram.from_state(name, h).snapshot().items():
            out[f"{name}.{k}"] = v
    return out


class ClusterSnapshot(NamedTuple):
    """`scrape_cluster` result: the exactly-merged flat snapshot plus each
    worker's raw state for per-host drill-down."""
    merged: dict
    workers: list   # [(ServiceInfo, raw state dict), ...]


def scrape_cluster(registry_address: str, name: Optional[str] = None,
                   timeout: float = 10.0,
                   skip_unreachable: bool = True) -> ClusterSnapshot:
    """Pull `/metrics.json` from every worker the `ServiceRegistry` at
    `registry_address` knows (optionally one service `name`) and merge.
    A worker that died between registering and the scrape is skipped (its
    numbers are gone either way); pass `skip_unreachable=False` to raise
    instead."""
    from ..io.registry import ServiceInfo, list_services
    if name is not None:
        infos = list_services(registry_address, name, timeout=timeout)
    else:
        with urllib.request.urlopen(registry_address + "/services",
                                    timeout=timeout) as resp:
            infos = [ServiceInfo(**d) for d in json.loads(resp.read())]
    workers = []
    for info in infos:
        try:
            with urllib.request.urlopen(info.address + "/metrics.json",
                                        timeout=timeout) as resp:
                workers.append((info, json.loads(resp.read())))
        except (OSError, ValueError) as e:
            if not skip_unreachable:
                raise RuntimeError(
                    f"scrape of {info.address} failed: {e}") from e
    merged = state_snapshot(merge_states([st for _, st in workers]))
    merged["telemetry.scrape.workers"] = len(workers)
    return ClusterSnapshot(merged=merged, workers=workers)
