"""Request-scoped span tracing: one id follows a request across threads,
processes, and HTTP hops.

Role analog: the reference stack leans on Spark's own event log plus ad-hoc
`log*` calls; a serving system meant for heavy traffic needs Dapper-style
spans — a *trace id* minted at ingress, *span ids* for each timed region,
parent linkage so the tree reconstructs, and propagation headers so the id
survives process boundaries (PAPERS.md: production serving/monitoring
stacks). This module is intentionally stdlib-only — it sits UNDER
`reliability`, `io`, `data`, and the model layers, so it must import none
of them.

Design:

- `Tracer` holds a bounded ring buffer (`collections.deque(maxlen=...)`) of
  FINISHED spans — a day of traffic cannot grow memory; overflow increments
  a `dropped` counter instead of blocking anything.
- Parent linkage rides a `contextvars.ContextVar`, so spans nest correctly
  across threads spawned with `contextvars.copy_context` and across the
  same thread's call stack; worker threads that process another thread's
  request activate its context explicitly (`tracer.use(span)`).
- **Deterministic head sampling**: the keep/drop decision is made ONCE at
  the trace head and is a pure function of `(trace_id, sample_rate)` —
  `crc32(trace_id)/2^32 < rate` — so every process that sees the same
  trace id independently reaches the same decision (no sampled-flag drift
  between hosts on the same trace). A propagated `X-Trace-Id` header also
  carries the decision explicitly, which wins over recomputation.
- **Tail-based capture (second stage)**: with `tail_latency_ms` set, a
  head-UNSAMPLED trace still records — tentatively, into a bounded
  pending buffer keyed by trace id — and the whole tree is promoted to
  the ring when its ROOT span finishes slow (>= threshold), errored, or
  with a 5xx status; fast clean traces are discarded wholesale. The 1%
  head sample stays a statistically honest picture of ALL traffic while
  every slow/failed request keeps a full span tree. Tentative traces
  never inject propagation headers (the local process can't promise the
  fleet a trace it may yet discard), and eviction is deterministic
  (oldest pending trace first; per-trace span cap) — see stats().
- Propagation: `X-Trace-Id: <trace_id>:<parent_span_id>:<0|1>`. A bare
  value with no `:` is accepted as a sampled trace id (curl-friendly).
- Zero overhead disabled: `sample_rate == 0` with no incoming context makes
  `start_span` return `None` after one float compare; every instrumentation
  site branches on `is not None`.
- JSONL export: `export_jsonl(path)` writes one JSON object per finished
  span, in `seq` order — a process-wide monotonic sequence number that
  makes single-process event logs causally ordered even when wall clocks
  are too coarse to order them.

Events (`tracer.event(name, **attrs)`) are zero-duration spans with
`kind="event"` — supervisor restarts/preemptions and FaultInjector firings
land here, so a chaos run reads as one ordered narrative.
"""
from __future__ import annotations

import contextlib
import contextvars
import functools
import itertools
import json
import os
import threading
import time
import uuid
import zlib
from collections import deque
from typing import Callable, NamedTuple, Optional

TRACE_HEADER = "X-Trace-Id"
REQUEST_ID_HEADER = "X-Request-Id"
# env knobs: sampling rate for the process-default tracer (0 = off, the
# production-safe default; serving tests/benches opt in), ring capacity,
# and the tail-capture latency threshold in ms (unset/<=0 = off)
SAMPLE_ENV = "MMLSPARK_TPU_TRACE_SAMPLE"
CAPACITY_ENV = "MMLSPARK_TPU_TRACE_CAPACITY"
TAIL_ENV = "MMLSPARK_TPU_TRACE_TAIL_MS"

# tail-capture bounds: pending traces awaiting their root's verdict, and
# spans buffered per pending trace (a runaway recursive trace must not
# grow memory); both deterministic — overflow evicts the OLDEST pending
# trace / drops further spans, counted in stats()
TAIL_PENDING_TRACES = 256
TAIL_SPANS_PER_TRACE = 512

_UNSET = object()


class SpanContext(NamedTuple):
    """The propagated identity of a trace position: enough to parent a new
    span (local or remote) and to carry the head-sampling decision."""
    trace_id: str
    span_id: str
    sampled: bool

    def header_value(self) -> str:
        return f"{self.trace_id}:{self.span_id}:{1 if self.sampled else 0}"


_current: contextvars.ContextVar = contextvars.ContextVar(
    "mmlspark_tpu_trace_ctx", default=None)


# One wall-clock anchor per process, captured once at import: epoch-valued
# timestamps are derived as anchor + perf_counter(), so they ADVANCE
# MONOTONICALLY — an NTP step mid-run cannot reorder span starts against
# their seq numbers, make a heartbeat look fresh/stale by hours, or
# interleave usage-log timestamps backwards. (graftlint's `wall-clock`
# rule points raw time.time() call sites here.)
_WALL_ANCHOR = time.time() - time.perf_counter()  # graftlint: disable=wall-clock


def wall_now() -> float:
    """Epoch-valued timestamp that advances monotonically (never steps
    backward with NTP): the process-start wall clock plus the monotonic
    perf_counter. Use for timestamps that get COMPARED or ordered —
    span starts, heartbeats, event logs."""
    return _WALL_ANCHOR + time.perf_counter()


def new_id() -> str:
    """16-hex span/trace id (uuid4-derived: unique without coordination)."""
    return uuid.uuid4().hex[:16]


def head_sampled(trace_id: str, rate: float) -> bool:
    """The deterministic head-sampling decision: a pure function of the
    trace id, so independent processes agree without a propagated flag."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode()) & 0xFFFFFFFF) / 4294967296.0 < rate


def parse_trace_header(value: str) -> Optional[SpanContext]:
    """`trace:parent_span:flag` (or a bare trace id, treated as sampled)."""
    if not value:
        return None
    parts = value.strip().split(":")
    if len(parts) == 1:
        return SpanContext(parts[0], "", True)
    if len(parts) >= 3:
        return SpanContext(parts[0], parts[1], parts[2] not in ("0", ""))
    return SpanContext(parts[0], parts[1], True)


class Span:
    """One timed region. Created by `Tracer.start_span` (never directly);
    lands in the tracer's ring buffer when `finish()` is called. Safe to
    finish from a different thread than the one that started it; finish is
    idempotent (serving's reply/expiry race may touch a span twice)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_s",
                 "attrs", "duration_ms", "kind", "_t0", "_tracer",
                 "_finished")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 attrs: Optional[dict]):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self._t0 = time.perf_counter()
        # derived from the same monotonic reading as duration: span starts
        # order consistently with seq even across an NTP step
        self.start_s = _WALL_ANCHOR + self._t0
        self.attrs = dict(attrs) if attrs else {}
        self.duration_ms = 0.0
        self.kind = "span"
        self._tracer = tracer
        self._finished = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, True)

    def finish(self, **attrs) -> None:
        # test-and-set under the tracer lock: the serving reply/expiry race
        # can call finish from two threads at once, and an unsynchronized
        # flag would append the span twice with conflicting statuses
        with self._tracer._lock:
            if self._finished:
                return
            self._finished = True
        self.duration_ms = (time.perf_counter() - self._t0) * 1000.0
        if attrs:
            self.attrs.update(attrs)
        self._tracer._append(self)

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start": self.start_s, "duration_ms": self.duration_ms,
                "kind": self.kind, "attrs": self.attrs}

    def __repr__(self):
        return (f"Span({self.name} trace={self.trace_id} id={self.span_id} "
                f"{self.duration_ms:.3f}ms)")


class Tracer:
    """Span factory + bounded ring of finished spans. Thread-safe."""

    def __init__(self, sample: Optional[float] = None,
                 capacity: Optional[int] = None,
                 tail_latency_ms: Optional[float] = _UNSET):
        if sample is None:
            sample = float(os.environ.get(SAMPLE_ENV, "0") or 0)
        if capacity is None:
            capacity = int(os.environ.get(CAPACITY_ENV, "4096") or 4096)
        if tail_latency_ms is _UNSET:
            tail = float(os.environ.get(TAIL_ENV, "0") or 0)
            tail_latency_ms = tail if tail > 0.0 else None
        self._lock = threading.Lock()
        self._sample = float(sample)
        self._spans: deque = deque(maxlen=max(int(capacity), 1))
        self._dropped = 0
        self._seq = itertools.count()
        # tail-capture second stage (see module docstring): head-unsampled
        # traces buffer here until their ROOT finishes, then the whole
        # tree is kept (breach) or discarded (fast + clean)
        self._tail_ms = (None if tail_latency_ms is None
                         else float(tail_latency_ms))
        self._pending: dict = {}    # trace_id -> {"root": sid, "spans": []}
        self._pending_cap = TAIL_PENDING_TRACES
        # evicted pending traces leave a bounded tombstone so their late
        # spans (children in flight, the root's eventual finish) are
        # dropped instead of leaking into the ring unsampled
        self._tombstones: dict = {}   # trace_id -> None, insertion-ordered
        self._tail_kept = 0
        self._tail_dropped = 0
        self._tail_evicted = 0

    # -- configuration -------------------------------------------------------
    @property
    def sample_rate(self) -> float:
        return self._sample

    @property
    def tail_latency_ms(self) -> Optional[float]:
        """Tail-capture threshold (ms); None = tail stage off."""
        return self._tail_ms

    def configure(self, sample: Optional[float] = None,
                  capacity: Optional[int] = None,
                  tail_latency_ms=_UNSET,
                  tail_pending: Optional[int] = None) -> "Tracer":
        with self._lock:
            if sample is not None:
                self._sample = float(sample)
            if capacity is not None:
                self._spans = deque(self._spans,
                                    maxlen=max(int(capacity), 1))
            if tail_latency_ms is not _UNSET:
                # None disables; a number (ms) enables the second stage
                self._tail_ms = (None if tail_latency_ms is None
                                 else float(tail_latency_ms))
                if self._tail_ms is None:
                    self._pending.clear()
                    self._tombstones.clear()
            if tail_pending is not None:
                self._pending_cap = max(int(tail_pending), 1)
        return self

    # -- context propagation -------------------------------------------------
    def current(self) -> Optional[SpanContext]:
        return _current.get()

    def extract(self, headers: Optional[dict]) -> Optional[SpanContext]:
        """Pull a SpanContext out of an HTTP header dict, case-insensitive:
        serving's selector transport lowercases keys, http.client sends
        them as given, and urllib CAPITALIZES to 'X-trace-id' — all three
        spellings must resolve or propagation silently drops."""
        if not headers:
            return None
        value = headers.get(TRACE_HEADER) or headers.get(TRACE_HEADER.lower())
        if value is None:
            low = TRACE_HEADER.lower()
            for k, v in headers.items():
                if isinstance(k, str) and k.lower() == low:
                    value = v
                    break
        if value is None:
            return None
        return parse_trace_header(value)

    def inject(self, headers: Optional[dict] = None,
               ctx: Optional[SpanContext] = None) -> dict:
        """Add the active (or given) SAMPLED context to an outbound header
        dict; returns {} / the dict unchanged when there is nothing to
        propagate — callers can merge unconditionally."""
        ctx = ctx if ctx is not None else _current.get()
        if headers is None:
            headers = {}
        if ctx is not None and ctx.sampled:
            # a TENTATIVE (tail-pending) trace must not propagate as
            # sampled: the header would force every downstream process to
            # record a trace whose fate this process hasn't decided yet.
            # Evicted/discarded traces (tombstoned) stay silent too —
            # their local spans are already gone.
            if ((self._pending and ctx.trace_id in self._pending)
                    or (self._tombstones
                        and ctx.trace_id in self._tombstones)):
                return headers
            headers[TRACE_HEADER] = ctx.header_value()
        return headers

    @contextlib.contextmanager
    def use(self, span_or_ctx):
        """Activate a span/context on THIS thread (worker threads processing
        another thread's request adopt its trace here)."""
        ctx = (span_or_ctx.context if isinstance(span_or_ctx, Span)
               else span_or_ctx)
        token = _current.set(ctx)
        try:
            yield ctx
        finally:
            _current.reset(token)

    # -- span creation -------------------------------------------------------
    def start_span(self, name: str, parent=_current,
                   trace_id: Optional[str] = None,
                   span_id: Optional[str] = None,
                   attrs: Optional[dict] = None) -> Optional[Span]:
        """Begin a span; returns None when the trace is unsampled (callers
        branch on `is not None` — the disabled path is one compare).

        `parent` defaults to the ambient contextvar; pass an explicit Span /
        SpanContext / None (None forces a new trace head). `trace_id` /
        `span_id` override generation — serving uses the ingress request id
        as both the fresh trace id and the root span id so the id a client
        sees IS the trace id."""
        if parent is _current:
            parent = _current.get()
        if isinstance(parent, Span):
            parent = parent.context
        if parent is not None:
            if not parent.sampled:
                return None
            tid, pid = parent.trace_id, parent.span_id or None
        else:
            tail = self._tail_ms
            if self._sample <= 0.0 and tail is None:
                return None
            tid = trace_id if trace_id is not None else new_id()
            pid = None
            if not head_sampled(tid, self._sample):
                if tail is None:
                    return None
                # tail second stage: record TENTATIVELY — the trace
                # buffers in _pending until this root span finishes, and
                # is kept only if the root breached (slow/error/5xx)
                sid = span_id or new_id()
                with self._lock:
                    if tid not in self._pending:
                        if len(self._pending) >= self._pending_cap:
                            # deterministic eviction: oldest pending trace
                            oldest = next(iter(self._pending))
                            gone = self._pending.pop(oldest)
                            self._tail_evicted += 1 + len(gone["spans"])
                            self._tombstone(oldest)
                        self._tombstones.pop(tid, None)
                        self._pending[tid] = {"root": sid, "spans": []}
                return Span(self, name, tid, sid, pid, attrs)
        return Span(self, name, tid, span_id or new_id(), pid, attrs)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Context-manager span, activated on the current thread; yields the
        Span or None. An escaping exception is recorded as `error=<type>`
        before re-raising — restarted/retried work stays visible."""
        sp = self.start_span(name, attrs=attrs or None)
        if sp is None:
            yield None
            return
        token = _current.set(sp.context)
        try:
            yield sp
        except BaseException as e:
            sp.finish(error=type(e).__name__)
            raise
        finally:
            _current.reset(token)
            sp.finish()

    def trace(self, name: Optional[str] = None, **attrs) -> Callable:
        """Decorator form: `@tracer.trace("stage.encode")`."""
        def deco(fn):
            label = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapped(*args, **kwargs):
                with self.span(label, **attrs):
                    return fn(*args, **kwargs)
            return wrapped
        return deco

    def record(self, name: str, parent=_current, duration_ms: float = 0.0,
               start_s: Optional[float] = None, kind: str = "span",
               attrs: Optional[dict] = None) -> Optional[dict]:
        """Append an already-measured span post-hoc (batch workers stamp one
        per request AFTER the shared transform ran; `observe` sinks land
        here). Sampling rules match start_span; returns the recorded dict
        or None."""
        sp = self.start_span(name, parent=parent, attrs=attrs)
        if sp is None:
            return None
        sp.kind = kind
        # a post-hoc span is recorded at the END of its interval: backdate
        # the start by the duration so children sit INSIDE their parent on
        # a timeline instead of dangling past its end
        sp.start_s = (start_s if start_s is not None
                      else sp.start_s - float(duration_ms) / 1000.0)
        sp.duration_ms = float(duration_ms)
        sp._finished = True
        self._append(sp)
        return sp.to_dict()

    def event(self, name: str, parent=_current, **attrs) -> Optional[dict]:
        """Point-in-time structured event (kind="event"): recorded under the
        active sampled trace, or as a trace of its own when sampling is on —
        supervisor preemptions and injected faults must appear in the chaos
        log even when no request context is active."""
        return self.record(name, parent=parent, duration_ms=0.0,
                           kind="event", attrs=attrs or None)

    def observe(self, label: str, seconds: float) -> Optional[dict]:
        """`(label, seconds)` sink — the same signature as
        `MetricsRegistry.observe`, so `utils.tracing.wall_clock(...,
        sink=tracer.observe)` turns timed blocks into spans. Returns the
        recorded span dict, or None when sampling dropped it — callers
        that REPLACE another output with the span (Timer's print) use
        this to fall back instead of losing the timing."""
        return self.record(label, duration_ms=seconds * 1000.0)

    # -- ring buffer / export ------------------------------------------------
    def _tombstone(self, trace_id: str) -> None:
        """Remember (bounded, oldest-out) that a tentative trace was
        evicted/discarded: its late spans drop instead of leaking into
        the ring unsampled, and it never injects headers. Caller holds
        the tracer lock."""
        if len(self._tombstones) >= self._pending_cap:
            self._tombstones.pop(next(iter(self._tombstones)))
        self._tombstones[trace_id] = None

    def _tail_breach(self, d: dict) -> bool:
        """Did this root span earn its trace a place in the ring? Slow
        (>= threshold), errored, or answered 5xx — 'every slow/failed
        request has a full span tree'."""
        tail = self._tail_ms
        if tail is not None and d["duration_ms"] >= tail:
            return True
        attrs = d["attrs"]
        if attrs.get("error") is not None:
            return True
        status = attrs.get("status")
        return isinstance(status, int) and status >= 500

    def _ring_append(self, d: dict) -> None:
        if len(self._spans) == self._spans.maxlen:
            self._dropped += 1
        self._spans.append(d)

    def _append(self, span: Span) -> None:
        d = span.to_dict()
        with self._lock:
            d["seq"] = next(self._seq)
            d["pid"] = os.getpid()
            if self._tombstones and span.trace_id in self._tombstones:
                self._tail_dropped += 1   # late span of an evicted trace
                return
            if self._pending:
                entry = self._pending.get(span.trace_id)
                if entry is not None:
                    if span.span_id == entry["root"]:
                        # the root's finish is the tail decision point
                        del self._pending[span.trace_id]
                        if self._tail_breach(d):
                            d["attrs"] = dict(d["attrs"], tail=True)
                            self._tail_kept += 1
                            for s in entry["spans"]:
                                self._ring_append(s)
                            self._ring_append(d)
                        else:
                            self._tail_dropped += 1 + len(entry["spans"])
                            # discarded wholesale means late stragglers
                            # too: a child finishing after its fast root
                            # must not leak into the ring
                            self._tombstone(span.trace_id)
                    elif len(entry["spans"]) < TAIL_SPANS_PER_TRACE:
                        entry["spans"].append(d)
                    else:
                        self._tail_dropped += 1
                    return
            self._ring_append(d)

    def finished(self, name: Optional[str] = None) -> list:
        """Finished span dicts in seq (causal) order; `name` filters."""
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s["name"] == name]
        return out

    def pending_tail(self) -> list:
        """Snapshot of the tail stage's TENTATIVE traces — the span trees
        still waiting on their root's verdict. The flight recorder dumps
        these next to the ring: at the moment of distress, the request
        most worth seeing is often the one still in flight.
        `[{"trace_id", "root", "spans": [...]}]`, insertion order."""
        with self._lock:
            return [{"trace_id": tid, "root": e["root"],
                     "spans": list(e["spans"])}
                    for tid, e in self._pending.items()]

    def export_jsonl(self, path: str, clear: bool = False) -> int:
        """Write the ring to a JSONL file (one span per line, seq order);
        returns the number of spans written."""
        spans = self.finished()
        with open(path, "w") as f:
            for s in spans:
                f.write(json.dumps(s) + "\n")
        if clear:
            self.clear()
        return len(spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._pending.clear()
            self._tombstones.clear()
            self._dropped = 0
            self._tail_kept = 0
            self._tail_dropped = 0
            self._tail_evicted = 0

    def stats(self) -> dict:
        with self._lock:
            return {"spans": len(self._spans), "dropped": self._dropped,
                    "capacity": self._spans.maxlen,
                    "sample_rate": self._sample,
                    "tail_latency_ms": self._tail_ms,
                    "tail_pending": len(self._pending),
                    "tail_kept": self._tail_kept,
                    "tail_dropped": self._tail_dropped,
                    "tail_evicted": self._tail_evicted}


def read_jsonl(path: str) -> list:
    """Load a JSONL export back into span dicts (test/analysis helper)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


# Process-wide default: instrumentation sites record here unless handed a
# private tracer (mirrors reliability_metrics). Sampling comes from
# MMLSPARK_TPU_TRACE_SAMPLE (default 0 = off; `configure(sample=...)`
# flips it at runtime).
_default = Tracer()


def get_tracer() -> Tracer:
    return _default


def configure(sample: Optional[float] = None,
              capacity: Optional[int] = None,
              tail_latency_ms=_UNSET,
              tail_pending: Optional[int] = None) -> Tracer:
    """Configure the process-default tracer (sampling rate / ring size /
    tail-capture threshold)."""
    return _default.configure(sample=sample, capacity=capacity,
                              tail_latency_ms=tail_latency_ms,
                              tail_pending=tail_pending)
