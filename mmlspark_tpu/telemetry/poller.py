"""TelemetryPoller: periodic fleet scrapes with bounded in-memory
retention.

`scrape_cluster` is a one-shot pull — it answers "what is the fleet doing
NOW" and forgets. The consumers ROADMAP items 3/4 describe need history:
the autotuner fits latency models per (op, shape-bucket) from *series*,
and autoscaling triggers on *sustained* occupancy, not one reading. The
poller is that substrate: a daemon thread polls every registered worker
on an interval (windowed metrics + the merged `/slo` verdict) and keeps
the last `history` samples in a ring (`collections.deque(maxlen=...)`) —
a day of polling cannot grow memory, same contract as the span ring.

Each sample is one flat dict (plus the fleet SLO verdict), so a series
read is a list comprehension and the JSONL export replays into any
offline fitting job:

    poller = TelemetryPoller(registry.address, interval_s=10, window_s=60)
    poller.start()
    ...
    poller.series("serving.request.e2e.p99")   # [(t, p99_ms), ...]
    poller.latest()["slo"]["ok"]
    poller.export_jsonl("/tmp/fleet.jsonl")
    poller.stop()

Scrape failures are counted (`telemetry.poll.errors`) and absorbed — a
registry hiccup leaves a gap in the series, never a dead poller.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Optional

from ..reliability.metrics import reliability_metrics
from . import names as tnames
from .exposition import scrape_cluster
from .spans import wall_now


def _newest_within(lines: list, max_bytes: int) -> list:
    """The newest suffix of `lines` whose total size fits `max_bytes`
    (the newest line always survives — a bound must truncate history,
    never the present)."""
    total = 0
    keep: list = []
    for line in reversed(lines):
        if keep and total + len(line) > max_bytes:
            break
        keep.append(line)
        total += len(line)
    keep.reverse()
    return keep


class TelemetryPoller:
    """Bounded-retention fleet poller (see module docstring)."""

    def __init__(self, registry_address: str, name: Optional[str] = None,
                 interval_s: float = 10.0, window_s: Optional[float] = 60.0,
                 history: int = 720, timeout: float = 5.0,
                 slo: bool = True, flight_on_burn: bool = False,
                 kind: Optional[str] = None,
                 jsonl_path: Optional[str] = None,
                 jsonl_max_bytes: int = 16 * 1024 * 1024,
                 clock=None, quality: bool = False,
                 versions: bool = False, on_sample=None):
        if interval_s <= 0.0:
            raise ValueError("interval_s must be > 0")
        self.registry_address = registry_address
        self.name = name
        # continuous JSONL sink with size-bounded rotation: every sample
        # appends one line; when the file exceeds jsonl_max_bytes the
        # OLDEST lines are dropped (atomic rewrite) — a watcher that
        # polls for weeks cannot fill the disk, same bounded-retention
        # contract as the in-memory deque
        self.jsonl_path = jsonl_path
        self.jsonl_max_bytes = max(int(jsonl_max_bytes), 1024)
        # injectable wall clock for sample timestamps (tests pin
        # retention/rotation without sleeping)
        self._clock = clock if clock is not None else wall_now
        # None polls every registered endpoint (serving AND trainers —
        # their registry `kind` entries make the mix explicit); set to
        # "serving"/"trainer" to watch one class
        self.kind = kind
        self.interval_s = float(interval_s)
        self.window_s = window_s
        self.timeout = float(timeout)
        self.slo = bool(slo)
        # quality=True also pulls each worker's /quality export and keeps
        # the fleet-merged result on the sample (sketch counts sum,
        # drift recomputed — telemetry/quality.py); the flat
        # quality.drift.* gauges ride the merged metrics either way
        self.quality = bool(quality)
        # versions=True also pulls each worker's /versions export and
        # keeps the fleet-merged result on the sample, plus the rollout
        # skew (how many workers currently serve each model version) —
        # a rollout that stalls half-deployed shows up as a persistent
        # two-entry skew, not as any single worker's metric
        self.versions = bool(versions)
        # fleet-side flight trigger: when the MERGED verdict transitions
        # to burning, dump a local debug bundle (telemetry/perf.py) — the
        # poller is the one process that sees the fleet burn even when no
        # single worker does
        self.flight_on_burn = bool(flight_on_burn)
        # actuator hook: called with (sample, snapshot) after each poll
        # round — the control loop's feed (e.g. a WeightedRouter's
        # update_from_scrape, a FleetScaler's observe). Exceptions are
        # absorbed as poll errors: an actuator bug leaves a gap in
        # actuation, never a dead poller.
        self.on_sample = on_sample
        self._samples: deque = deque(maxlen=max(int(history), 1))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "TelemetryPoller":
        if self._thread is not None:
            raise RuntimeError("poller already started")
        self._stop.clear()   # a stopped poller may be restarted
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-poller")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout + 10.0)
            self._thread = None

    def _loop(self) -> None:
        # first sample immediately, then every interval; Event.wait is
        # the sleep AND the stop signal (no polling loop inside a lock)
        while True:
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - gap in the series, not death
                reliability_metrics.inc(tnames.TELEMETRY_POLL_ERRORS)
            if self._stop.wait(self.interval_s):
                return

    # -- sampling ------------------------------------------------------------
    def poll_once(self) -> dict:
        """One scrape round (also callable without start() for manual
        cadence). Raises on scrape failure — the loop absorbs, callers
        see the error."""
        snap = scrape_cluster(self.registry_address, name=self.name,
                              timeout=self.timeout, window=self.window_s,
                              slo=self.slo, kind=self.kind,
                              quality=self.quality,
                              versions=self.versions)
        sample = {"t": self._clock(),
                  "workers": snap.merged.get("telemetry.scrape.workers", 0),
                  "window_s": snap.merged.get("telemetry.scrape.window_s"),
                  "metrics": snap.merged,
                  "slo": snap.slo}
        if self.quality:
            sample["quality"] = snap.quality
        if self.versions:
            sample["versions"] = snap.versions
            if snap.versions:
                from .lineage import rollout_skew
                sample["rollout_skew"] = rollout_skew(
                    snap.versions.get("current_by_worker", {}))
        with self._lock:
            self._samples.append(sample)
        reliability_metrics.inc(tnames.TELEMETRY_POLL_SAMPLES)
        if self.jsonl_path is not None:
            # outside the lock: disk I/O must never serialize readers.
            # Failures count as poll errors but keep the in-memory series
            # (the loop absorbs; manual poll_once callers see them too)
            try:
                self._append_jsonl(sample)
            except OSError:
                reliability_metrics.inc(tnames.TELEMETRY_POLL_ERRORS)
        if self.flight_on_burn and snap.slo is not None:
            try:
                from .perf import get_flight_recorder
                # the recorder owns the transition latch (source="fleet"
                # keeps it independent of the local engine's burns) and
                # never raises
                get_flight_recorder().on_verdict(
                    snap.slo, reason="fleet-slo-burn", source="fleet")
            except Exception:  # noqa: BLE001 - the series continues
                pass
        if self.on_sample is not None:
            try:
                self.on_sample(sample, snap)
            except Exception:  # noqa: BLE001 - actuators never kill polls
                reliability_metrics.inc(tnames.TELEMETRY_POLL_ERRORS)
        return sample

    # -- read side -----------------------------------------------------------
    def samples(self) -> list:
        """All retained samples, oldest first."""
        with self._lock:
            return list(self._samples)

    def latest(self) -> Optional[dict]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def series(self, key: str) -> list:
        """[(t, value), ...] for one merged-metric key across retained
        samples; samples missing the key are skipped (a worker fleet that
        hasn't emitted the metric yet leaves a gap, not a zero)."""
        out = []
        for s in self.samples():
            v = s["metrics"].get(key)
            if v is not None:
                out.append((s["t"], v))
        return out

    def export_jsonl(self, path: str,
                     max_bytes: Optional[int] = None) -> int:
        """One sample per line, oldest first — the offline-fitting feed
        (same convention as `Tracer.export_jsonl`). `max_bytes` bounds
        the file by dropping the OLDEST samples first (the newest always
        survives)."""
        samples = self.samples()
        lines = [json.dumps(s) + "\n" for s in samples]
        if max_bytes is not None:
            lines = _newest_within(lines, max_bytes)
        with open(path, "w") as f:
            f.writelines(lines)
        return len(lines)

    def _append_jsonl(self, sample: dict) -> None:
        """Append one sample line; rotate (oldest lines dropped, atomic
        tmp+replace) when the file exceeds `jsonl_max_bytes`."""
        line = json.dumps(sample) + "\n"
        with open(self.jsonl_path, "a") as f:
            f.write(line)
        if os.path.getsize(self.jsonl_path) <= self.jsonl_max_bytes:
            return
        with open(self.jsonl_path) as f:
            lines = f.readlines()
        # rotate down to HALF the bound: trimming to exactly max_bytes
        # would leave the file full and re-trigger this whole-file
        # read+rewrite on every subsequent append — halving amortizes
        # the rewrite to once per ~half-bound of new samples
        keep = _newest_within(lines, self.jsonl_max_bytes // 2)
        tmp = self.jsonl_path + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(keep)
        os.replace(tmp, self.jsonl_path)

    def stats(self) -> dict:
        with self._lock:
            return {"samples": len(self._samples),
                    "capacity": self._samples.maxlen,
                    "interval_s": self.interval_s,
                    "running": self._thread is not None
                    and self._thread.is_alive()}
