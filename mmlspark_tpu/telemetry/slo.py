"""SLO engine: declared objectives, multi-window burn rates, mergeable
verdicts.

An SLO turns a latency histogram into a yes/no question a control plane
can act on: "p99 of `serving.request.e2e` under 250 ms over the last
60 s" or "5xx rate under 1%". The classic formulation (Google SRE
workbook; *CTA-Pipelining*'s scale-for-tail-latency argument in
PAPERS.md) is *error-budget burn rate*:

- a latency objective at quantile q allows a fraction `1 - q/100` of
  requests over the threshold. The observed over-threshold fraction
  divided by that allowance is the burn rate — burn 1.0 means exactly
  on budget, 10.0 means the budget burns ten times too fast.
- an error-rate objective's burn is `observed_rate / budget`.

Each objective is evaluated over TWO windows — the declared one and a
`long_factor` multiple — and `burning` requires both over 1.0: the short
window gives fast detection, the long window stops a single slow request
from flapping the verdict (multi-window, multi-burn-rate alerting).

Everything reads the windowed shards `telemetry/window.py` attaches to
the process registry, so the verdict reflects the last N seconds, not
process history. Violation counts come from histogram BUCKETS (count of
observations in buckets above the threshold's bucket), which makes
worker verdicts mergeable the same way histograms are: `merge_verdicts`
sums counts across workers and recomputes rates/burns — never averages
— mirroring `scrape_cluster`'s bucket-merge discipline. The threshold
snaps down to a bucket boundary (~6% relative), the same resolution the
percentiles already carry.

`GET /slo` on every `ServingServer` (and the `ServiceRegistry`) returns
`verdict()` as JSON; `scrape_cluster(slo=True)` pulls and merges them
fleet-wide.
"""
from __future__ import annotations

import threading
from bisect import bisect_right
from typing import NamedTuple, Optional

from ..reliability.metrics import (Histogram, histogram_bounds_ms,
                                   reliability_metrics)
from . import names as tnames

LATENCY = "latency"
ERROR_RATE = "error_rate"
GOODPUT = "goodput"
QUALITY = "quality"


class Objective(NamedTuple):
    """One declared objective. `kind` is `latency` (histogram `metric`,
    `quantile` of requests must finish under `threshold_ms`),
    `error_rate` (counter `metric` over counter `total_metric` must stay
    under `budget`), `goodput` (gauge `metric` must stay at or above
    `floor` — the training-side floor on productive wall-clock
    fraction), or `quality` (a model-quality gauge from
    telemetry/quality.py: a drift gauge bounded above by `ceiling`, or a
    streaming-eval metric bounded below by `floor`). `window_s` is the
    short evaluation window; a gauge objective reads the same last-set
    value in both windows (gauges carry no shards — the StepClock /
    quality sketches already window their own inputs)."""
    name: str
    kind: str
    metric: str
    window_s: float = 60.0
    threshold_ms: float = 0.0      # latency only
    quantile: float = 99.0         # latency only
    budget: float = 0.01           # error_rate only
    total_metric: str = ""         # error_rate only
    floor: float = 0.0             # goodput / quality metric floor
    ceiling: float = 0.0           # quality drift bound (value must stay <=)


def default_objectives() -> list:
    """The serving-tier defaults: e2e p99 under 250 ms over 60 s, and a
    1% budget on 5xx/shed responses. Replace with `configure()`."""
    return [
        Objective(name="serving.e2e.p99", kind=LATENCY,
                  metric=tnames.SERVING_REQUEST_E2E,
                  threshold_ms=250.0, quantile=99.0, window_s=60.0),
        Objective(name="serving.error_rate", kind=ERROR_RATE,
                  metric=tnames.SERVING_REQUEST_ERRORS,
                  total_metric=tnames.SERVING_REQUEST_TOTAL,
                  budget=0.01, window_s=60.0),
    ]


def trainer_objectives(goodput_floor: float = 0.9,
                       window_s: float = 60.0) -> list:
    """The training-tier default: goodput (productive/wall, the
    `train.goodput` gauge the StepClock publishes) must stay at or above
    `goodput_floor`. Trainers mount it with
    `configure(default_objectives() + trainer_objectives())` or through
    `telemetry.exposition.expose_trainer(goodput_floor=...)`."""
    return [
        Objective(name="train.goodput.floor", kind=GOODPUT,
                  metric=tnames.TRAIN_GOODPUT, floor=goodput_floor,
                  window_s=window_s),
    ]


def quality_objectives(drift_ceiling: float = 0.25,
                       metric_floor: Optional[float] = None,
                       metric: str = "quality.eval.accuracy",
                       window_s: float = 60.0) -> list:
    """The model-quality objectives (telemetry/quality.py): the worst
    per-column PSI (`quality.drift.max`, refreshed on every scrape) must
    stay at or below `drift_ceiling` — 0.25 is the classic
    "distribution shifted" PSI bound — and, with `metric_floor` set, the
    streaming-eval gauge `metric` must stay at or above it. Ceiling
    objectives merge on the WORST (max) worker, floor objectives on the
    worst (min) — never averaged, like goodput."""
    out = [Objective(name="quality.drift", kind=QUALITY,
                     metric=tnames.QUALITY_DRIFT_MAX,
                     ceiling=drift_ceiling, window_s=window_s)]
    if metric_floor is not None:
        out.append(Objective(name="quality.metric.floor", kind=QUALITY,
                             metric=metric, floor=metric_floor,
                             window_s=window_s))
    return out


def canary_objectives(p99_ratio_max: float = 2.0,
                      error_burn_max: float = 1.0,
                      drift_delta_max: float = 0.25,
                      window_s: float = 60.0) -> list:
    """The canary objectives (telemetry/lineage.py): candidate-vs-
    incumbent ceilings over the scrape-refreshed canary gauges — the
    candidate's windowed p99 must stay under `p99_ratio_max` x the
    incumbent's frozen p99, its server-fault rate under `error_burn_max`
    x the canary error budget, and its live drift within
    `drift_delta_max` PSI of the incumbent's frozen drift. All three
    gauges are ABSENT until a hot-swap has produced an incumbent AND a
    candidate, and a no-data window burns 0 — a fleet that never swapped
    cannot trip its canary. This is the rollback *signal* (verdict ->
    FlightRecorder, `versions.json` in the bundle); actuation stays with
    the control plane (ROADMAP item 3)."""
    return [Objective(name="canary.p99", kind=QUALITY,
                      metric=tnames.CANARY_P99_RATIO,
                      ceiling=p99_ratio_max, window_s=window_s),
            Objective(name="canary.errors", kind=QUALITY,
                      metric=tnames.CANARY_ERROR_BURN,
                      ceiling=error_burn_max, window_s=window_s),
            Objective(name="canary.drift", kind=QUALITY,
                      metric=tnames.CANARY_DRIFT_DELTA,
                      ceiling=drift_delta_max, window_s=window_s)]


def _violations_over(counts: list, threshold_ms: float) -> int:
    """Observations in buckets strictly above the threshold's bucket —
    the merge-safe over-threshold count (threshold snaps DOWN to its
    bucket's upper edge, so this slightly undercounts rather than
    flapping the verdict on boundary noise)."""
    bounds = histogram_bounds_ms()
    idx = bisect_right(bounds, threshold_ms)
    return sum(counts[idx + 1:])


class SLOEngine:
    """Evaluates objectives against a registry's windowed shards and
    renders the machine-readable verdict `/slo` serves."""

    def __init__(self, objectives: Optional[list] = None, registry=None,
                 long_factor: float = 5.0):
        self.objectives = list(objectives if objectives is not None
                               else default_objectives())
        self._registry = (registry if registry is not None
                          else reliability_metrics)
        self.long_factor = float(long_factor)

    # -- per-window measurement ----------------------------------------------
    def _latency_window(self, obj: Objective, window_s: float) -> dict:
        # peek, never create: evaluating an SLO on a process that has
        # not recorded the metric (the registry leader, a fresh worker)
        # must not materialize zero-count serving series there
        hist = self._registry.peek_histogram(obj.metric)
        if hist is None or hist.window is None:
            return {"window_s": window_s, "count": 0, "violations": 0,
                    "no_window": True}
        state = hist.window.state(window_s)
        violations = _violations_over(state["counts"], obj.threshold_ms)
        value = (Histogram.from_state(obj.metric, state)
                 .percentile(obj.quantile) if state["count"] else 0.0)
        return {"window_s": window_s, "count": state["count"],
                "violations": violations, "value_ms": value}

    def _gauge_window(self, obj: Objective, window_s: float) -> dict:
        # a gauge is a last-set value, not a shard ring: both windows
        # read the same number (the StepClock's goodput is already a
        # cumulative-with-recent-median signal). peek, never create —
        # a never-trained process reads as no-data, not goodput 0.
        value = self._registry.peek_gauge(obj.metric)
        if value is None:
            return {"window_s": window_s, "no_data": True}
        return {"window_s": window_s, "value": float(value)}

    def _error_window(self, obj: Objective, window_s: float) -> dict:
        total = self._registry.peek_counter(obj.total_metric)
        if total is None or total.window is None:
            return {"window_s": window_s, "total": 0, "errors": 0,
                    "no_window": True}
        # an errors counter that was never created just means zero
        # errors so far — the denominator is still real traffic
        errors = self._registry.peek_counter(obj.metric)
        err_n = (errors.window.total(window_s)
                 if errors is not None and errors.window is not None
                 else 0)
        return {"window_s": window_s, "errors": err_n,
                "total": total.window.total(window_s)}

    def verdict(self, notify: bool = True) -> dict:
        """The per-worker SLO verdict: every objective with per-window
        counts (mergeable), rates, burn rates, and the ok/burning flags.
        `ok` is the short window within budget; `burning` is EVERY
        window over budget (sustained burn).

        Every evaluation notifies the flight recorder (telemetry/perf.py)
        so an ok->burning TRANSITION dumps a debug bundle at the moment
        of distress; `notify=False` is for readers that must not
        re-trigger it (the recorder itself, capturing the verdict for
        the bundle it is writing)."""
        out = []
        for obj in self.objectives:
            windows = []
            for w in (obj.window_s, obj.window_s * self.long_factor):
                if obj.kind == LATENCY:
                    m = self._latency_window(obj, w)
                elif obj.kind in (GOODPUT, QUALITY):
                    m = self._gauge_window(obj, w)
                else:
                    m = self._error_window(obj, w)
                windows.append(_finish_window(obj._asdict(), m))
            burning = all(w["burn_rate"] > 1.0 for w in windows)
            out.append({"objective": obj._asdict(), "windows": windows,
                        "ok": windows[0]["burn_rate"] <= 1.0,
                        "burning": burning})
        result = {"objectives": out,
                  "ok": all(o["ok"] for o in out),
                  "burning": any(o["burning"] for o in out),
                  "workers": 1}
        if notify:
            # lazy + guarded: the verdict must render even if the
            # recorder (or its disk) is broken, and a disabled recorder
            # costs one attribute read
            try:
                from .perf import get_flight_recorder
                get_flight_recorder().on_verdict(result)
            except Exception:  # noqa: BLE001
                pass
        return result


def _finish_window(obj: dict, m: dict) -> dict:
    """Rate/burn math for one window measurement — shared by the live
    engine and the fleet merge so both always agree."""
    m = dict(m)
    if obj["kind"] in (GOODPUT, QUALITY):
        # gauge objectives: burn > 1 exactly when the gauge crosses its
        # bound — below the floor (goodput, a metric floor) or above the
        # ceiling (a drift bound). No data (never trained / no live
        # traffic folded) burns 0 — absence of evidence is not a burn
        value = m.get("value")
        floor = obj.get("floor", 0.0)
        ceiling = obj.get("ceiling", 0.0)
        if value is None:
            m["rate"], m["burn_rate"] = 0.0, 0.0
        else:
            m["rate"] = value
            if ceiling > 0:
                m["burn_rate"] = value / ceiling
            elif floor > 0:
                m["burn_rate"] = floor / max(value, 1e-9)
            else:
                m["burn_rate"] = 0.0
        return m
    if obj["kind"] == LATENCY:
        count, violations = m.get("count", 0), m.get("violations", 0)
        allowed = max(1.0 - obj["quantile"] / 100.0, 1e-9)
        rate = violations / count if count else 0.0
    else:
        count, violations = m.get("total", 0), m.get("errors", 0)
        allowed = max(obj["budget"], 1e-9)
        rate = violations / count if count else 0.0
    m["rate"] = rate
    m["burn_rate"] = rate / allowed
    return m


def verdict_burning(verdict: Optional[dict]) -> bool:
    """None-safe read of a verdict's fleet-level `burning` flag — the
    one-liner every actuator (burn-aware admission, the rollout driver)
    keys on. A missing/empty verdict reads NOT burning: actuation must
    fail open (keep serving) when the sensor is dark, never shed on a
    scrape gap."""
    return bool(verdict) and bool(verdict.get("burning"))


def merge_verdicts(verdicts: list) -> Optional[dict]:
    """Fleet-wide verdict from per-worker verdicts: per-objective,
    per-window counts SUM across workers and rates/burns are recomputed
    from the sums (a 2-worker fleet where one worker burns 2x and one 0x
    burns 1x overall — averaging the burn rates would say the same here
    but diverges the moment traffic is uneven). `value_ms` cannot be
    merged without buckets, so the merged view reports the worst worker
    as `value_ms_max` — labeled, not silently averaged."""
    verdicts = [v for v in verdicts if v]
    if not verdicts:
        return None
    by_name: dict = {}
    order: list = []
    for v in verdicts:
        for o in v.get("objectives", ()):
            name = o["objective"]["name"]
            agg = by_name.get(name)
            if agg is None:
                agg = by_name[name] = {
                    "objective": dict(o["objective"]),
                    "windows": [dict(w) for w in o["windows"]]}
                for w in agg["windows"]:
                    if "value_ms" in w:
                        w["value_ms_max"] = w.pop("value_ms")
                order.append(name)
                continue
            for wa, wb in zip(agg["windows"], o["windows"]):
                for key in ("count", "violations", "errors", "total"):
                    if key in wb:
                        wa[key] = wa.get(key, 0) + wb[key]
                if "value_ms" in wb:
                    wa["value_ms_max"] = max(wa.get("value_ms_max", 0.0),
                                             wb["value_ms"])
                if "value" in wb:
                    # gauge objectives: the WORST worker is the fleet
                    # verdict — min for a floor (goodput, metric floor),
                    # MAX for a ceiling (drift bound) — never averaged
                    pick = (max if agg["objective"].get("ceiling", 0.0) > 0
                            else min)
                    wa["value"] = (pick(wa["value"], wb["value"])
                                   if "value" in wa else wb["value"])
                    wa.pop("no_data", None)
    objectives = []
    for name in order:
        agg = by_name[name]
        windows = [_finish_window(agg["objective"], w)
                   for w in agg["windows"]]
        objectives.append({
            "objective": agg["objective"], "windows": windows,
            "ok": windows[0]["burn_rate"] <= 1.0,
            "burning": all(w["burn_rate"] > 1.0 for w in windows)})
    return {"objectives": objectives,
            "ok": all(o["ok"] for o in objectives),
            "burning": any(o["burning"] for o in objectives),
            "workers": sum(v.get("workers", 1) for v in verdicts)}


# Process-wide default engine (mirrors reliability_metrics / the default
# tracer): `/slo` mounts read it; `configure()` swaps the objectives.
_engine: Optional[SLOEngine] = None
_engine_lock = threading.Lock()


def get_engine() -> SLOEngine:
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = SLOEngine()
        return _engine


def configure(objectives: Optional[list] = None,
              long_factor: Optional[float] = None) -> SLOEngine:
    """Replace the process-default objectives (None restores defaults)."""
    global _engine
    with _engine_lock:
        current = _engine
        _engine = SLOEngine(
            objectives=objectives,
            long_factor=(long_factor if long_factor is not None
                         else (current.long_factor if current else 5.0)))
        return _engine
