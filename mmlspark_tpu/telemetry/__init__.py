"""Telemetry subsystem: request-scoped span tracing, cross-process metrics
exposition, and profiling hooks (docs/observability.md).

Three pillars:

- **Spans** (`telemetry.spans`): `Tracer`/`Span` with contextvar parent
  linkage, deterministic head sampling, a bounded ring buffer, JSONL
  export, and `X-Trace-Id` propagation — one id follows a request from
  serving ingress through the partition queue and compiled-plan transform
  to the reply, and from `RegistryClient` posts into the registry.
- **Exposition** (`telemetry.exposition`): Prometheus text + JSON
  rendering of `reliability.metrics.MetricsRegistry`, mounted as
  `/metrics` / `/metrics.json` on `ServingServer` and `ServiceRegistry`,
  plus `scrape_cluster()` which pulls and exactly merges every registered
  worker's snapshot (bucket-level histogram merge, not percentile
  averaging).
- **Hooks**: serving request path, `data.DevicePrefetcher`,
  `TrainingSupervisor` step/checkpoint lifecycle, `fit_booster`
  iterations, `utils.tracing.trace` device profiles (stamped with the
  active trace id), and structured events for supervisor
  restarts/preemptions and `FaultInjector` firings — chaos runs read as
  one causally-ordered event log.

Sampling defaults OFF (env `MMLSPARK_TPU_TRACE_SAMPLE`, or
`telemetry.configure(sample=...)`): at 0% the hot-path cost is a single
compare per site (`BENCH_MODE=telemetry` pins the off/1%/full A/B).
"""
from .spans import (CAPACITY_ENV, REQUEST_ID_HEADER, SAMPLE_ENV, Span,
                    SpanContext, TRACE_HEADER, Tracer, configure, get_tracer,
                    head_sampled, new_id, parse_trace_header, read_jsonl,
                    wall_now)

# exposition re-exports are LAZY: spans.py is the stdlib-only layer every
# subsystem imports (`from ..telemetry.spans import get_tracer`), and that
# import executes this __init__ — an eager exposition import would pull
# reliability.metrics into every low layer and re-open the circular-import
# door spans.py exists to close.
_EXPOSITION_NAMES = frozenset((
    "ClusterSnapshot", "PROM_CONTENT_TYPE", "merge_states",
    "metrics_http_response", "render_prometheus", "scrape_cluster",
    "state_snapshot"))


def __getattr__(name):
    if name in _EXPOSITION_NAMES:
        from . import exposition
        return getattr(exposition, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = ["Tracer", "Span", "SpanContext", "get_tracer", "configure",
           "head_sampled", "new_id", "parse_trace_header", "read_jsonl",
           "wall_now",
           "TRACE_HEADER", "REQUEST_ID_HEADER", "SAMPLE_ENV", "CAPACITY_ENV",
           "render_prometheus", "metrics_http_response", "merge_states",
           "state_snapshot", "scrape_cluster", "ClusterSnapshot",
           "PROM_CONTENT_TYPE"]
