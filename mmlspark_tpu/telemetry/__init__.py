"""Telemetry subsystem: request-scoped span tracing, cross-process metrics
exposition, windowed aggregation, SLO burn rates, tail-based trace
capture, and profiling hooks (docs/observability.md).

Pillars:

- **Spans** (`telemetry.spans`): `Tracer`/`Span` with contextvar parent
  linkage, deterministic head sampling, a bounded ring buffer, JSONL
  export, and `X-Trace-Id` propagation — one id follows a request from
  serving ingress through the partition queue and compiled-plan transform
  to the reply, and from `RegistryClient` posts into the registry.
- **Exposition** (`telemetry.exposition`): Prometheus text + JSON
  rendering of `reliability.metrics.MetricsRegistry`, mounted as
  `/metrics` / `/metrics.json` on `ServingServer` and `ServiceRegistry`,
  plus `scrape_cluster()` which pulls and exactly merges every registered
  worker's snapshot (bucket-level histogram merge, not percentile
  averaging).
- **Windows** (`telemetry.window`): a ring of per-interval shards under
  every counter/histogram — `/metrics.json?window=60` and
  `MetricsRegistry.window_snapshot()` answer with percentiles over the
  LAST N seconds (bounded memory, shard-merged, never averaged).
- **SLOs** (`telemetry.slo`): declared objectives (latency quantile
  bounds, error-rate budgets) evaluated as multi-window burn rates over
  the windowed shards; `GET /slo` per worker, merged fleet-wide by
  `scrape_cluster(slo=True)`.
- **Tail capture** (`telemetry.spans`): a second sampling stage that
  retroactively keeps the full span tree of any trace whose root
  finished slow, errored, or 5xx — coexists with the deterministic 1%
  head sample.
- **Retention** (`telemetry.poller`): `TelemetryPoller` polls the fleet
  on an interval and keeps a bounded JSONL-exportable series — the
  autotuner/control-plane data substrate.
- **Performance** (`telemetry.perf`): compile/cost telemetry with a
  recompile detector, device/host memory gauges sampled on every
  scrape, per-bucket trace exemplars on histograms, and the
  burn-triggered flight recorder (`GET /debug/bundle`).
- **Device profiles** (`telemetry.profiler`): triggered on-device
  capture (`GET /debug/profile`, straggler flags, burn latches) parsed
  into per-op records and joined with compile-log cost into the
  per-region roofline ledger (`op.<region>.*` gauges, roofline.json).
- **Watch** (`telemetry.watch`): threshold + median-shift change-point
  detection over poller series — live regressions trip events and
  flight bundles instead of waiting for the next offline benchdiff.
- **Quality** (`telemetry.quality`): mergeable streaming distribution
  sketches on the serving stream, PSI/JS drift against the fit-time
  reference profile (`quality.drift.*` gauges, `GET /quality`,
  `scrape_cluster(quality=True)`), and a delayed-label join feeding
  streaming evaluation through the batch `ComputeModelStatistics`
  metric kernels — the semantic tier over the systems telemetry.
- **Lineage** (`telemetry.lineage`): content-addressed model versions
  (structural + fitted-array digests) with fit-time provenance, the
  bounded per-version metric splits behind `GET /versions`, the
  candidate-vs-incumbent canary gauges (`canary.*`), rollout-skew from
  `scrape_cluster(versions=True)`, and the append-only `RunLedger` —
  deployment observability over the serving hot-swap
  (`ServingTransform.install_model`).
- **Hooks**: serving request path, `data.DevicePrefetcher`,
  `TrainingSupervisor` step/checkpoint lifecycle, `fit_booster`
  iterations, `utils.tracing.trace` device profiles (stamped with the
  active trace id), and structured events for supervisor
  restarts/preemptions and `FaultInjector` firings — chaos runs read as
  one causally-ordered event log.

Sampling defaults OFF (env `MMLSPARK_TPU_TRACE_SAMPLE`, or
`telemetry.configure(sample=...)`): at 0% the hot-path cost is a single
compare per site (`BENCH_MODE=telemetry` pins the off/1%/full A/B).
"""
from .spans import (CAPACITY_ENV, REQUEST_ID_HEADER, SAMPLE_ENV, Span,
                    SpanContext, TAIL_ENV, TRACE_HEADER, Tracer, configure,
                    get_tracer, head_sampled, new_id, parse_trace_header,
                    read_jsonl, wall_now)

# exposition/window/slo/poller re-exports are LAZY: spans.py is the
# stdlib-only layer every subsystem imports
# (`from ..telemetry.spans import get_tracer`), and that import executes
# this __init__ — an eager import here would pull reliability.metrics into
# every low layer and re-open the circular-import door spans.py exists to
# close.
_LAZY_NAMES = {
    "ClusterSnapshot": "exposition", "PROM_CONTENT_TYPE": "exposition",
    "merge_states": "exposition", "metrics_http_response": "exposition",
    "render_prometheus": "exposition", "scrape_cluster": "exposition",
    "state_snapshot": "exposition",
    "ExpositionServer": "exposition", "expose_trainer": "exposition",
    "WindowedCounter": "window", "WindowedHistogram": "window",
    "Objective": "slo", "SLOEngine": "slo", "default_objectives": "slo",
    "merge_verdicts": "slo", "trainer_objectives": "slo",
    "quality_objectives": "slo", "canary_objectives": "slo",
    "TelemetryPoller": "poller",
    "ModelVersion": "lineage", "RunLedger": "lineage",
    "model_version": "lineage", "configure_run_ledger": "lineage",
    "get_run_ledger": "lineage",
    "get_version_registry": "lineage", "reset_version_registry": "lineage",
    "export_versions": "lineage", "merge_version_exports": "lineage",
    "refresh_canary_gauges": "lineage", "rollout_skew": "lineage",
    "canary_watch_rules": "lineage",
    "QualityMonitor": "quality", "DatasetProfile": "quality",
    "FeatureSketch": "quality", "StreamingEvaluator": "quality",
    "get_monitor": "quality", "reset_monitor": "quality",
    "configure_quality": "quality", "export_quality": "quality",
    "refresh_quality_gauges": "quality",
    "merge_quality_exports": "quality", "drift_scores": "quality",
    "psi": "quality", "js_divergence": "quality",
    "quality_watch_rules": "quality", "record_label": "quality",
    "StepClock": "goodput", "StragglerDetector": "goodput",
    "flops_from_compile_log": "goodput",
    "ProfileSession": "profiler", "RooflineLedger": "profiler",
    "get_profile_session": "profiler",
    "configure_profile_session": "profiler",
    "capture_profile": "profiler", "parse_trace": "profiler",
    "get_roofline": "profiler", "resolve_peaks": "profiler",
    "WatchRule": "watch", "TelemetryWatcher": "watch",
    "CompileLog": "perf", "FlightRecorder": "perf", "AotCache": "perf",
    "collective_traffic": "perf",
    "compile_with_analysis": "perf", "executable_analysis": "perf",
    "record_plan_compile": "perf", "get_compile_log": "perf",
    "compile_stats": "perf", "hbm_utilization": "perf",
    "sample_resource_gauges": "perf", "sample_resource_stats": "perf",
    "get_flight_recorder": "perf", "configure_flight_recorder": "perf",
    "trigger_bundle": "perf",
}


def __getattr__(name):
    mod = _LAZY_NAMES.get(name)
    if mod is not None:
        import importlib
        return getattr(importlib.import_module(f".{mod}", __name__), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = ["Tracer", "Span", "SpanContext", "get_tracer", "configure",
           "head_sampled", "new_id", "parse_trace_header", "read_jsonl",
           "wall_now",
           "TRACE_HEADER", "REQUEST_ID_HEADER", "SAMPLE_ENV", "CAPACITY_ENV",
           "TAIL_ENV",
           "render_prometheus", "metrics_http_response", "merge_states",
           "state_snapshot", "scrape_cluster", "ClusterSnapshot",
           "PROM_CONTENT_TYPE", "ExpositionServer", "expose_trainer",
           "WindowedHistogram", "WindowedCounter",
           "Objective", "SLOEngine", "default_objectives", "merge_verdicts",
           "trainer_objectives", "quality_objectives", "canary_objectives",
           "TelemetryPoller",
           "ModelVersion", "RunLedger", "model_version",
           "configure_run_ledger", "get_run_ledger",
           "get_version_registry", "reset_version_registry",
           "export_versions", "merge_version_exports",
           "refresh_canary_gauges", "rollout_skew", "canary_watch_rules",
           "QualityMonitor", "DatasetProfile", "FeatureSketch",
           "StreamingEvaluator", "get_monitor", "reset_monitor",
           "configure_quality", "export_quality", "refresh_quality_gauges",
           "merge_quality_exports", "drift_scores", "psi", "js_divergence",
           "quality_watch_rules", "record_label",
           "StepClock", "StragglerDetector", "flops_from_compile_log",
           "CompileLog", "FlightRecorder", "AotCache", "collective_traffic",
           "compile_with_analysis",
           "executable_analysis", "record_plan_compile", "get_compile_log",
           "compile_stats", "hbm_utilization", "sample_resource_gauges",
           "sample_resource_stats", "get_flight_recorder",
           "configure_flight_recorder", "trigger_bundle",
           "ProfileSession", "RooflineLedger", "get_profile_session",
           "configure_profile_session", "capture_profile", "parse_trace",
           "get_roofline", "resolve_peaks",
           "WatchRule", "TelemetryWatcher"]
