"""Image ops: resize / unroll / augment + the OpenCV stage-DSL transformer.

Reference mapping:
- `ResizeImageTransformer` (image/ResizeImageTransformer.scala:22-130):
  batched bilinear resize — jax.image.resize on device (the reference uses
  java.awt on the JVM; the TPU-first version keeps whole batches on device).
- `UnrollImage` (image/UnrollImage.scala:26-235): (N,H,W,C) image batch ->
  flat (N, C*H*W) CHW-order vectors with BGR channel handling + optional
  normalization, matching the reference's CNTK input convention.
- `ImageSetAugmenter` (image/ImageSetAugmenter.scala:19-80): flip-LR/UD
  dataset expansion.
- `ImageTransformer` (opencv/ImageTransformer.scala:27-221): ordered stage
  DSL (resize, centerCrop, colorFormat, flip, blur, threshold,
  gaussianKernel) executed with cv2 per batch — same engine family as the
  reference's Imgproc path.
- `read_image_dir`: spark.read.image equivalent over a local directory
  (io/IOImplicits.scala) returning (path, image) columns.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..core import Param, Table, Transformer, HasInputCol, HasOutputCol
from ..core.params import one_of


def read_image_dir(path: str, pattern: str = "", decode=True) -> Table:
    """Directory of images -> Table(path, image) with uint8 (N,H,W,C) images
    when shapes agree, else an object column (reference: spark.read.image).
    Images decode via PIL; non-images are skipped like dropInvalid."""
    from PIL import Image
    paths, imgs = [], []
    for name in sorted(os.listdir(path)):
        if pattern and pattern not in name:
            continue
        full = os.path.join(path, name)
        try:
            with Image.open(full) as im:
                imgs.append(np.asarray(im.convert("RGB")))
            paths.append(full)
        except Exception:  # noqa: BLE001 - dropInvalid semantics
            continue
    if imgs and all(i.shape == imgs[0].shape for i in imgs):
        arr = np.stack(imgs)
    else:
        arr = np.empty(len(imgs), dtype=object)
        for i, im in enumerate(imgs):
            arr[i] = im
    return Table({"path": np.asarray(paths, dtype=object), "image": arr})


def _to_batch(col: np.ndarray) -> np.ndarray:
    """Accept (N,H,W,C) stacked or object column of (H,W,C) arrays."""
    if col.dtype == object:
        return np.stack([np.asarray(v) for v in col])
    return col


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    width = Param("width", "target width", 224)
    height = Param("height", "target height", 224)

    def __init__(self, **kw):
        kw.setdefault("input_col", "image")
        kw.setdefault("output_col", "image")
        super().__init__(**kw)

    def _transform(self, t: Table) -> Table:
        import jax
        import jax.numpy as jnp
        imgs = _to_batch(t[self.input_col]).astype(np.float32)
        n = imgs.shape[0]
        out = jax.image.resize(jnp.asarray(imgs),
                               (n, self.height, self.width, imgs.shape[-1]),
                               method="bilinear")
        return t.with_column(self.output_col,
                             np.asarray(out).clip(0, 255).astype(np.uint8))


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """(N,H,W,C) -> (N, C*H*W) CHW-order float vectors, RGB->BGR like the
    reference's CNTK convention, with optional scaling/normalization."""
    to_bgr = Param("to_bgr", "swap to BGR channel order", True)
    scale = Param("scale", "multiply pixel values (e.g. 1/255)", 1.0)
    mean = Param("mean", "per-channel mean to subtract (len C)", None)
    std = Param("std", "per-channel std to divide (len C)", None)

    def __init__(self, **kw):
        kw.setdefault("input_col", "image")
        kw.setdefault("output_col", "features")
        super().__init__(**kw)

    def _transform(self, t: Table) -> Table:
        imgs = _to_batch(t[self.input_col]).astype(np.float32)
        if self.to_bgr:
            imgs = imgs[..., ::-1]
        imgs = imgs * self.scale
        if self.mean is not None:
            imgs = imgs - np.asarray(self.mean, np.float32)
        if self.std is not None:
            imgs = imgs / np.asarray(self.std, np.float32)
        n, h, w, c = imgs.shape
        chw = imgs.transpose(0, 3, 1, 2)  # CHW like UnrollImage.scala
        return t.with_column(self.output_col, chw.reshape(n, c * h * w))


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    flip_left_right = Param("flip_left_right", "add LR-flipped copies", True)
    flip_up_down = Param("flip_up_down", "add UD-flipped copies", False)

    def __init__(self, **kw):
        kw.setdefault("input_col", "image")
        kw.setdefault("output_col", "image")
        super().__init__(**kw)

    def _transform(self, t: Table) -> Table:
        imgs = _to_batch(t[self.input_col])
        tables = [t.with_column(self.output_col, imgs)]
        other = {n: t[n] for n in t.columns if n != self.output_col}
        if self.flip_left_right:
            tables.append(Table({**other, self.output_col: imgs[:, :, ::-1]},
                                t.npartitions))
        if self.flip_up_down:
            tables.append(Table({**other, self.output_col: imgs[:, ::-1]},
                                t.npartitions))
        aligned = [tb.select(tables[0].columns) for tb in tables]
        return Table.concat_all(aligned)


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Ordered OpenCV stage DSL (reference: opencv/ImageTransformer.scala):

        ImageTransformer().resize(224, 224).center_crop(200, 200)
            .color_format("gray").flip(1).blur(5, 5)
            .threshold(127, 255).gaussian_kernel(3, 1.0)
    """
    stages = Param("stages", "ordered list of (op, kwargs) pairs", None)

    def __init__(self, **kw):
        kw.setdefault("input_col", "image")
        kw.setdefault("output_col", "image")
        super().__init__(**kw)
        if self.stages is None:
            self.set(stages=[])

    # fluent builders (reference: ImageTransformer.scala:282-380)
    def _add(self, op: str, **kwargs):
        self.set(stages=list(self.stages or []) + [[op, kwargs]])
        return self

    def resize(self, height: int, width: int):
        return self._add("resize", height=height, width=width)

    def center_crop(self, height: int, width: int):
        return self._add("crop", height=height, width=width)

    def color_format(self, fmt: str):
        return self._add("color", format=fmt)

    def flip(self, flip_code: int = 1):
        return self._add("flip", flip_code=flip_code)

    def blur(self, height: int, width: int):
        return self._add("blur", height=height, width=width)

    def threshold(self, threshold: float, max_val: float = 255.0,
                  threshold_type: int = 0):
        return self._add("threshold", threshold=threshold, max_val=max_val,
                         threshold_type=threshold_type)

    def gaussian_kernel(self, aperture_size: int, sigma: float):
        return self._add("gaussian", aperture_size=aperture_size, sigma=sigma)

    def _transform(self, t: Table) -> Table:
        import cv2
        imgs = _to_batch(t[self.input_col])
        out = []
        for img in imgs:
            x = np.asarray(img)
            for op, kw in (self.stages or []):
                if op == "resize":
                    x = cv2.resize(x, (kw["width"], kw["height"]),
                                   interpolation=cv2.INTER_LINEAR)
                elif op == "crop":
                    h, w = x.shape[:2]
                    ch, cw = kw["height"], kw["width"]
                    top = max((h - ch) // 2, 0)
                    left = max((w - cw) // 2, 0)
                    x = x[top:top + ch, left:left + cw]
                elif op == "color":
                    code = {"gray": cv2.COLOR_RGB2GRAY,
                            "bgr": cv2.COLOR_RGB2BGR}[kw["format"]]
                    x = cv2.cvtColor(x, code)
                elif op == "flip":
                    x = cv2.flip(x, kw["flip_code"])
                elif op == "blur":
                    x = cv2.blur(x, (kw["width"], kw["height"]))
                elif op == "threshold":
                    _, x = cv2.threshold(x, kw["threshold"], kw["max_val"],
                                         kw["threshold_type"])
                elif op == "gaussian":
                    k = kw["aperture_size"]
                    x = cv2.GaussianBlur(x, (k, k), kw["sigma"])
                else:
                    raise ValueError(f"unknown ImageTransformer op {op!r}")
            out.append(x)
        if out and all(o.shape == out[0].shape for o in out):
            col: np.ndarray = np.stack(out)
        else:
            col = np.empty(len(out), dtype=object)
            for i, o in enumerate(out):
                col[i] = o
        return t.with_column(self.output_col, col)
