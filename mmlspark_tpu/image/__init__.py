"""Image pipeline stages (reference: image/ + opencv/ — SURVEY.md §2.5)."""
from .ops import (ImageSetAugmenter, ImageTransformer, ResizeImageTransformer,
                  UnrollImage, read_image_dir)

__all__ = ["ImageSetAugmenter", "ImageTransformer", "ResizeImageTransformer",
           "UnrollImage", "read_image_dir"]
