"""Recovery-counter registry: one process-wide place where every resilience
path (retries, breaker trips, replayed epochs, shed requests, corrupt
checkpoints skipped) records what it survived.

Role analog: the reference surfaces recovery behavior only through logs; a
production serving stack needs the counters queryable (ROADMAP north star:
heavy traffic means recovery events are routine, not exceptional). The
registry doubles as a `utils.tracing.wall_clock` sink — `registry.observe`
has the `(label, seconds)` sink signature, so timed blocks land next to the
counters they explain:

    with tracing.wall_clock("replay", sink=reliability_metrics.observe):
        ...
    reliability_metrics.snapshot()
    # {"replay.seconds": 0.013, "replay.count": 1, "serving.replayed_epochs": 1}

Latency claims need distributions, not totals: `Histogram` is a bounded
geometric-bucket (HDR-style) latency histogram — O(1) memory, lock-guarded
integer increments, ~6% relative quantile error across 1 us .. 80 s. The
serving hot path records `serving.request.{queue,transform,reply,e2e}`
through it; `snapshot()` exposes each histogram's p50/p95/p99 so a latency
percentile is one dict read away. `set_gauge` holds last-value operational
signals (queue depth, batch occupancy).
"""
from __future__ import annotations

import os
import threading
from bisect import bisect_right
from typing import Optional

# Windowed-shard defaults (telemetry/window.py attaches a ring of
# per-interval shards to every counter/histogram the registry creates):
# 10 s intervals x 31 shards = 300 s of history, enough for the SLO
# engine's long burn window. Interval/shard count are env-tunable;
# shards <= 0 disables windowing entirely.
WINDOW_INTERVAL_ENV = "MMLSPARK_TPU_WINDOW_INTERVAL"
WINDOW_SHARDS_ENV = "MMLSPARK_TPU_WINDOW_SHARDS"
_WINDOW_INTERVAL_DEFAULT = 10.0
_WINDOW_SHARDS_DEFAULT = 31


class Counter:
    """Monotonic counter; thread-safe. `window` (attached by the registry
    from telemetry/window.py) mirrors increments into a time-sharded ring
    so recent-rate reads don't require tracking counter deltas."""

    __slots__ = ("name", "_value", "_lock", "window")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()
        self.window = None

    def inc(self, n: int = 1) -> int:
        with self._lock:
            self._value += n
            value = self._value
        w = self.window
        if w is not None:
            w.inc(n)
        return value

    @property
    def value(self) -> int:
        return self._value

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


# Shared bucket bounds (milliseconds): 256 geometric buckets spanning
# 1 us .. 80 s. One module-level tuple — histograms hold counts only.
_HIST_LO_MS = 1e-3
_HIST_HI_MS = 8e4
_HIST_BUCKETS = 256
_HIST_RATIO = (_HIST_HI_MS / _HIST_LO_MS) ** (1.0 / (_HIST_BUCKETS - 1))
_HIST_BOUNDS = tuple(_HIST_LO_MS * _HIST_RATIO ** i
                     for i in range(_HIST_BUCKETS - 1))


def histogram_bounds_ms() -> tuple:
    """The shared geometric bucket upper bounds (ms) every Histogram uses —
    public so telemetry exposition can render cumulative Prometheus buckets
    and merge cross-process states without poking privates."""
    return _HIST_BOUNDS


class Histogram:
    """Bounded-bucket latency histogram (HDR-style geometric buckets).

    `observe_ms` is O(log buckets) via bisect and never allocates;
    `percentile(p)` returns the geometric midpoint of the bucket holding
    the p-th sample, clamped to the observed min/max — bounded relative
    error regardless of how many samples arrive (the reason over a raw
    sample list: a day of traffic must not grow memory).

    **External bucket grids**: by default every Histogram shares the
    module-level geometric latency grid (1 us .. 80 s); `bounds` swaps in
    an externally-built grid of strictly-increasing upper edges — the
    value-domain form the quality sketches (telemetry/quality.py) build
    from reference-data quantiles. A custom-grid histogram keeps the
    whole mergeable-state contract: `state()` carries the grid under
    `"bounds"`, `from_state()` reconstructs it, and the round-trip is
    exact for the empty (all-zero counts, `min_ms: None`) and
    single-observation edges (pinned in tests/test_quality.py). Negative
    values are legal on a custom grid (feature domains are signed;
    latency's clamp-at-zero applies only to the default grid), and
    `percentile` falls back to the arithmetic bucket midpoint where a
    geometric one is undefined (lo <= 0). Custom-grid histograms live
    OUTSIDE the MetricsRegistry (exposition renders only the shared
    grid); `.state()` keys stay `sum_ms`/`min_ms`/`max_ms` for wire
    compatibility even when the unit is not milliseconds.

    **Merging**: `merge_state(state)` folds another histogram's raw state
    into this one — bucket counts sum elementwise (grids must match
    exactly), count/sum add, min/max extend; never averaged. It is the
    single merge kernel the cross-worker scrape merge and the quality
    sketches' chunk/fleet folds both reduce to.

    **Trace exemplars**: an observation that carries a `trace_id` leaves
    a last-per-bucket exemplar `(trace_id, ms, wall_ts)` — the link from
    a burning p99 bucket back to the tail-captured span tree of a request
    that landed in it. Bounded by construction (at most one slot per
    bucket, 256 total) and cheap by construction (the lock-held cost is
    one dict slot write; windowed shards carry NO exemplars). Callers
    that have no per-observation identity simply omit `trace_id` and pay
    nothing."""

    __slots__ = ("name", "_counts", "_count", "_sum_ms", "_min_ms",
                 "_max_ms", "_lock", "window", "_exemplars", "_bounds")

    def __init__(self, name: str, bounds: Optional[tuple] = None):
        self.name = name
        if bounds is None:
            self._bounds = _HIST_BOUNDS
        else:
            b = tuple(float(x) for x in bounds)
            if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
                raise ValueError(
                    "bounds must be a non-empty strictly-increasing grid "
                    "of bucket upper edges")
            self._bounds = b
        self._counts = [0] * (len(self._bounds) + 1)
        self._count = 0
        self._sum_ms = 0.0
        self._min_ms = float("inf")
        # custom (value-domain) grids may be all-negative: the running
        # max must start below any legal observation there. The default
        # latency grid keeps 0.0 (observations are clamped >= 0 and the
        # empty-state export stays byte-identical to older writers).
        self._max_ms = 0.0 if self._bounds is _HIST_BOUNDS \
            else float("-inf")
        self._lock = threading.Lock()
        # time-sharded ring (telemetry/window.py), attached by the
        # registry: cumulative and windowed views share ONE bisect per
        # observation (the shards reuse this histogram's bucket index)
        self.window = None
        self._exemplars: dict = {}   # bucket idx -> (trace_id, ms, ts)

    def observe_ms(self, ms: float, trace_id: Optional[str] = None) -> None:
        if ms < 0.0 and self._bounds is _HIST_BOUNDS:
            # the latency grid starts at 0; custom (value-domain) grids
            # carry signed observations unclamped
            ms = 0.0
        idx = bisect_right(self._bounds, ms)
        if trace_id is not None:
            # timestamped OUTSIDE the lock (one perf_counter read); only
            # exemplar-carrying observations pay it
            from ..telemetry.spans import wall_now
            ex = (trace_id, ms, wall_now())
        else:
            ex = None
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum_ms += ms
            if ms < self._min_ms:
                self._min_ms = ms
            if ms > self._max_ms:
                self._max_ms = ms
            if ex is not None:
                self._exemplars[idx] = ex   # last writer wins, one slot
        w = self.window
        if w is not None:
            w.observe_idx(idx, ms)

    def observe(self, seconds: float) -> None:
        self.observe_ms(seconds * 1000.0)

    def exemplars(self) -> dict:
        """{bucket_index: (trace_id, ms, wall_ts)} — the last exemplar
        per bucket."""
        with self._lock:
            return dict(self._exemplars)

    @property
    def count(self) -> int:
        return self._count

    def percentile(self, p: float) -> float:
        """Latency (ms) at percentile p in [0, 100]; 0.0 when empty."""
        with self._lock:
            if not self._count:
                return 0.0
            target = max(1, int(round(self._count * p / 100.0)))
            seen = 0
            for idx, c in enumerate(self._counts):
                seen += c
                if seen >= target:
                    if idx >= len(self._bounds):
                        return self._max_ms   # open-ended overflow bucket
                    lo = self._bounds[idx - 1] if idx > 0 else 0.0
                    hi = self._bounds[idx]
                    if lo > 0.0:
                        rep = (lo * hi) ** 0.5
                    elif self._bounds is not _HIST_BOUNDS:
                        # custom grids may span <= 0 where a geometric
                        # midpoint is undefined — arithmetic midpoint,
                        # still clamped to the observed range below
                        rep = (lo + hi) / 2.0
                    else:
                        rep = hi
                    return min(max(rep, self._min_ms), self._max_ms)
            return self._max_ms  # unreachable: counts sum to _count

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self._count, self._sum_ms
            observed_max = self._max_ms if self._count else 0.0
        mean = total / count if count else 0.0
        # `sum`/`mean` (ms) let exposition compute rates without re-walking
        # buckets; existing keys stay stable (mean_ms == mean, kept for
        # older readers). `p999`/`max` expose the extreme tail burn-rate
        # math and the autotuner steer on.
        return {"count": count,
                "mean_ms": mean,
                "sum": total,
                "mean": mean,
                "p50": self.percentile(50.0),
                "p95": self.percentile(95.0),
                "p99": self.percentile(99.0),
                "p999": self.percentile(99.9),
                "max": observed_max}

    # -- raw state (exposition / cross-process merge) -------------------------
    def state(self) -> dict:
        """Raw bucket counts + aggregates — the mergeable form. Default
        histograms share the module-level bounds, so merging two states is
        an elementwise count sum; a custom external grid rides along under
        `"bounds"` so `from_state` round-trips it exactly (the shared grid
        is omitted for wire compatibility). The round-trip holds at the
        edges: an EMPTY histogram exports all-zero counts with
        `min_ms: None`, and a single observation exports its exact value
        as both min and max. Exemplars ride along (JSON keys are strings)
        when any exist; merges keep the newest per bucket."""
        with self._lock:
            out = {"counts": list(self._counts), "count": self._count,
                   "sum_ms": self._sum_ms,
                   "min_ms": self._min_ms if self._count else None,
                   "max_ms": self._max_ms if self._count else 0.0}
            if self._bounds is not _HIST_BOUNDS:
                out["bounds"] = list(self._bounds)
            if self._exemplars:
                out["exemplars"] = {str(i): list(e)
                                    for i, e in self._exemplars.items()}
        return out

    @classmethod
    def from_state(cls, name: str, state: dict) -> "Histogram":
        bounds = state.get("bounds")
        h = cls(name, bounds=tuple(bounds) if bounds is not None else None)
        counts = list(state["counts"])
        if len(counts) != len(h._counts):
            raise ValueError(
                f"histogram state has {len(counts)} buckets, expected "
                f"{len(h._counts)} (mixed framework versions, or a state "
                f"from a different external grid?)")
        h._counts = [int(c) for c in counts]
        h._count = int(state["count"])
        h._sum_ms = float(state["sum_ms"])
        mn = state.get("min_ms")
        h._min_ms = float("inf") if mn is None else float(mn)
        if h._count:
            h._max_ms = float(state.get("max_ms", 0.0))
        # empty: keep the constructor's sentinel so later observations
        # (negative ones included, on custom grids) still set the max
        for i, e in (state.get("exemplars") or {}).items():
            h._exemplars[int(i)] = tuple(e)
        return h

    def merge_state(self, state: dict) -> "Histogram":
        """Fold another histogram's `state()` into this one: bucket counts
        sum elementwise, count/sum add, min/max extend — counts sum, never
        averaged (the scrape-merge discipline, available per instance so
        the quality sketches can fold chunk and worker states through ONE
        kernel). Grids must match exactly; a mismatched grid raises rather
        than silently mis-binning. Exemplars keep the newest per bucket."""
        bounds = state.get("bounds")
        if bounds is not None:
            if tuple(float(b) for b in bounds) != tuple(self._bounds):
                raise ValueError(
                    f"cannot merge histogram states over different bucket "
                    f"grids ({self.name})")
        elif self._bounds is not _HIST_BOUNDS:
            raise ValueError(
                f"cannot merge a default-grid state into the external-grid "
                f"histogram {self.name}")
        counts = state["counts"]
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram state has {len(counts)} buckets, expected "
                f"{len(self._counts)}")
        mn = state.get("min_ms")
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += int(c)
            self._count += int(state["count"])
            self._sum_ms += float(state["sum_ms"])
            if mn is not None and float(mn) < self._min_ms:
                self._min_ms = float(mn)
            mx = float(state.get("max_ms", 0.0))
            if int(state["count"]) and mx > self._max_ms:
                self._max_ms = mx
            for i, e in (state.get("exemplars") or {}).items():
                idx = int(i)
                prev = self._exemplars.get(idx)
                if prev is None or float(e[2]) >= float(prev[2]):
                    self._exemplars[idx] = tuple(e)
        return self

    @property
    def bounds(self) -> tuple:
        """This histogram's bucket upper edges (the shared latency grid
        unless an external grid was passed at construction)."""
        return tuple(self._bounds)

    def __repr__(self):
        return (f"Histogram({self.name}: n={self._count}, "
                f"p50={self.percentile(50.0):.3f}ms)")


class MetricsRegistry:
    """Named counters, histograms, gauges + wall-clock observations.
    All methods thread-safe.

    Every counter/histogram also carries a WINDOWED view (a ring of
    per-interval shards, telemetry/window.py): `window_state(window_s)` /
    `export_state(window_s=...)` return the same mergeable shape as the
    cumulative export but covering only the last N seconds — the
    decision-grade signal admission control and autoscaling need (a
    cumulative percentile mixes the first request with the millionth)."""

    def __init__(self, window_interval_s: Optional[float] = None,
                 window_shards: Optional[int] = None):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._timings: dict = {}   # label -> [total_seconds, count]
        self._hists: dict = {}     # name -> Histogram
        self._gauges: dict = {}    # name -> float (last value wins)
        if window_interval_s is None:
            window_interval_s = float(os.environ.get(
                WINDOW_INTERVAL_ENV, _WINDOW_INTERVAL_DEFAULT)
                or _WINDOW_INTERVAL_DEFAULT)
        if window_shards is None:
            window_shards = int(os.environ.get(
                WINDOW_SHARDS_ENV, _WINDOW_SHARDS_DEFAULT)
                or _WINDOW_SHARDS_DEFAULT)
        self._win_interval = float(window_interval_s)
        self._win_shards = int(window_shards)

    # -- windowed shards ------------------------------------------------------
    @property
    def window_span_s(self) -> float:
        """Guaranteed windowed history: the current shard is partial, so
        only interval * (shards - 1) seconds are always covered."""
        if self._win_shards <= 1 or self._win_interval <= 0.0:
            return 0.0
        return self._win_interval * (self._win_shards - 1)

    def _attach_window(self, metric, kind: str) -> None:
        """Give a fresh counter/histogram its time-sharded ring. Lazy
        import: telemetry/window.py imports THIS module at its top level,
        so the upward reference must resolve at call time, not import
        time (same pattern as the exposition mounts in io/serving.py)."""
        if self._win_shards <= 1 or self._win_interval <= 0.0:
            return
        from ..telemetry.window import WindowedCounter, WindowedHistogram
        cls = WindowedHistogram if kind == "hist" else WindowedCounter
        metric.window = cls(self._win_interval, self._win_shards)

    def configure_windows(self, interval_s: float, shards: int) -> None:
        """Re-shard every windowed view (tests shrink the interval to make
        roll-off observable without waiting wall-clock minutes). Existing
        windowed contents are discarded — cumulative state is untouched."""
        with self._lock:
            self._win_interval = float(interval_s)
            self._win_shards = int(shards)
            metrics = ([(h, "hist") for h in self._hists.values()]
                       + [(c, "counter") for c in self._counters.values()])
        for metric, kind in metrics:
            metric.window = None
            self._attach_window(metric, kind)

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
                self._attach_window(c, "counter")
            return c

    def inc(self, name: str, n: int = 1) -> int:
        return self.counter(name).inc(n)

    def peek_counter(self, name: str) -> Optional[Counter]:
        """Non-creating lookup — readers (the SLO engine, exposition)
        must not materialize metrics on processes that never record
        them."""
        with self._lock:
            return self._counters.get(name)

    def get(self, name: str) -> int:
        with self._lock:
            c = self._counters.get(name)
        return c.value if c is not None else 0

    # -- tracing sink --------------------------------------------------------
    def observe(self, label: str, seconds: float) -> None:
        """`utils.tracing.wall_clock(label, sink=registry.observe)`."""
        with self._lock:
            t = self._timings.setdefault(label, [0.0, 0])
            t[0] += seconds
            t[1] += 1

    # -- histograms ----------------------------------------------------------
    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name)
                self._attach_window(h, "hist")
            return h

    def peek_histogram(self, name: str) -> Optional[Histogram]:
        """Non-creating lookup (see peek_counter)."""
        with self._lock:
            return self._hists.get(name)

    def observe_ms(self, name: str, ms: float,
                   trace_id: Optional[str] = None) -> None:
        self.histogram(name).observe_ms(ms, trace_id=trace_id)

    def percentile(self, name: str, p: float) -> float:
        with self._lock:
            h = self._hists.get(name)
        return h.percentile(p) if h is not None else 0.0

    # -- gauges --------------------------------------------------------------
    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def peek_gauge(self, name: str) -> Optional[float]:
        """Non-creating, absence-preserving lookup: None means the gauge
        was never set (a goodput SLO on a process that never trained must
        read as no-data, not as goodput 0.0)."""
        with self._lock:
            return self._gauges.get(name)

    # -- read side -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            out = {name: c.value for name, c in self._counters.items()}
            for label, (total, count) in self._timings.items():
                out[f"{label}.seconds"] = total
                out[f"{label}.count"] = count
            hists = list(self._hists.items())
            out.update(self._gauges)
        # histogram percentile math takes the per-histogram lock, not the
        # registry lock — observers on the hot path never wait on snapshot
        for name, h in hists:
            for k, v in h.snapshot().items():
                out[f"{name}.{k}"] = v
        return out

    def export_state(self, window_s: Optional[float] = None) -> dict:
        """JSON-serializable raw state: counters/timings/gauges plus each
        histogram's bucket counts — what `/metrics.json` ships and
        `telemetry.exposition.merge_states` sums across workers (snapshot()
        percentiles cannot be merged; bucket counts can, exactly).

        `window_s` switches counters and histograms to their WINDOWED
        view (last N seconds, shard-aligned) in the same mergeable shape;
        the effective window rides along as `window_s` (clamped to the
        ring's guaranteed span). Timings and gauges have no windowed form
        and are passed through cumulative/last-value."""
        if window_s is not None:
            return self.window_state(window_s)
        with self._lock:
            counters = {n: c.value for n, c in self._counters.items()}
            timings = {l: list(t) for l, t in self._timings.items()}
            gauges = dict(self._gauges)
            hists = list(self._hists.items())
        return {"counters": counters, "timings": timings, "gauges": gauges,
                "hists": {n: h.state() for n, h in hists}}

    def window_state(self, window_s: float) -> dict:
        """Windowed raw state (see export_state). Metrics created before
        windowing was enabled — or with windowing disabled — are omitted
        rather than silently reported cumulative."""
        span = self.window_span_s
        eff = min(float(window_s), span) if span > 0.0 else 0.0
        with self._lock:
            counters = list(self._counters.items())
            timings = {l: list(t) for l, t in self._timings.items()}
            gauges = dict(self._gauges)
            hists = list(self._hists.items())
        out = {"window_s": eff, "window_requested_s": float(window_s),
               "counters": {}, "timings": timings, "gauges": gauges,
               "hists": {}}
        for name, c in counters:
            if c.window is not None:
                out["counters"][name] = c.window.total(eff)
        for name, h in hists:
            if h.window is not None:
                out["hists"][name] = h.window.state(eff)
        return out

    def window_snapshot(self, window_s: float) -> dict:
        """Flat snapshot()-shaped view of the last N seconds — windowed
        percentiles are recomputed from the merged shard buckets, never
        averaged across shards."""
        from ..telemetry.exposition import state_snapshot
        return state_snapshot(self.window_state(window_s))

    def reset(self, prefix: Optional[str] = None) -> None:
        """Zero counters/timings/histograms/gauges (tests isolate scenarios
        with this). `prefix` limits the reset to one subsystem's names."""
        with self._lock:
            if prefix is None:
                self._counters.clear()
                self._timings.clear()
                self._hists.clear()
                self._gauges.clear()
                return
            for store in (self._counters, self._timings, self._hists,
                          self._gauges):
                for name in [n for n in store if n.startswith(prefix)]:
                    del store[name]


# Process-wide default: library code records here unless handed a private
# registry (mirrors how the stage registry / shared singletons work).
reliability_metrics = MetricsRegistry()
