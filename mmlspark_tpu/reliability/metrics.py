"""Recovery-counter registry: one process-wide place where every resilience
path (retries, breaker trips, replayed epochs, shed requests, corrupt
checkpoints skipped) records what it survived.

Role analog: the reference surfaces recovery behavior only through logs; a
production serving stack needs the counters queryable (ROADMAP north star:
heavy traffic means recovery events are routine, not exceptional). The
registry doubles as a `utils.tracing.wall_clock` sink — `registry.observe`
has the `(label, seconds)` sink signature, so timed blocks land next to the
counters they explain:

    with tracing.wall_clock("replay", sink=reliability_metrics.observe):
        ...
    reliability_metrics.snapshot()
    # {"replay.seconds": 0.013, "replay.count": 1, "serving.replayed_epochs": 1}
"""
from __future__ import annotations

import threading
from typing import Optional


class Counter:
    """Monotonic counter; thread-safe."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> int:
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self) -> int:
        return self._value

    def __repr__(self):
        return f"Counter({self.name}={self._value})"


class MetricsRegistry:
    """Named counters + wall-clock observations. All methods thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._timings: dict = {}   # label -> [total_seconds, count]

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def inc(self, name: str, n: int = 1) -> int:
        return self.counter(name).inc(n)

    def get(self, name: str) -> int:
        with self._lock:
            c = self._counters.get(name)
        return c.value if c is not None else 0

    # -- tracing sink --------------------------------------------------------
    def observe(self, label: str, seconds: float) -> None:
        """`utils.tracing.wall_clock(label, sink=registry.observe)`."""
        with self._lock:
            t = self._timings.setdefault(label, [0.0, 0])
            t[0] += seconds
            t[1] += 1

    # -- read side -----------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            out = {name: c.value for name, c in self._counters.items()}
            for label, (total, count) in self._timings.items():
                out[f"{label}.seconds"] = total
                out[f"{label}.count"] = count
        return out

    def reset(self, prefix: Optional[str] = None) -> None:
        """Zero counters/timings (tests isolate scenarios with this).
        `prefix` limits the reset to one subsystem's names."""
        with self._lock:
            if prefix is None:
                self._counters.clear()
                self._timings.clear()
                return
            for name in [n for n in self._counters if n.startswith(prefix)]:
                del self._counters[name]
            for name in [n for n in self._timings if n.startswith(prefix)]:
                del self._timings[name]


# Process-wide default: library code records here unless handed a private
# registry (mirrors how the stage registry / shared singletons work).
reliability_metrics = MetricsRegistry()
