"""Elastic multi-host training: lease-based liveness, coordinated fleet
checkpoints, and shrink-resume.

PR 17 made a SLOW host lose its chunks (straggler detection ->
`ChunkPlanner.reassign`); a DEAD host was still fatal — its last
heartbeat row returned forever, pending chunks stayed assigned to it,
and there was no fleet-consistent checkpoint for the survivors to resume
from. This module closes the loop with three pieces
(docs/reliability.md "Elastic multi-host training"):

1. **HostLeases** — each observed `Heartbeat.beat()` renews a lease on
   the OBSERVER's monotonic clock; a lease aging past `lease_timeout_s`
   is a death verdict. No cross-host wall-clock comparison anywhere: a
   host is dead when *this observer* has seen no new beat content for
   the timeout, whatever the writer's clock said. The verdict bumps the
   shared epoch fence (`parallel.cluster.bump_fence`), so a zombie that
   resumes beating is rejected (`FencedOut`) instead of corrupting the
   plan; `train.host.dead` fires on the transition and
   `cluster.hosts.{live,dead}` gauges stay current.
2. **FleetCheckpoint** — two-phase commit over a shared directory:
   phase 1, every host's `AsyncCheckpointWriter` lands its step-k shard
   under `host_<pid>/` (the single-host digest/fsync discipline,
   `utils.checkpoint.CheckpointManager`, unchanged); phase 2, the leader
   (lowest live process_id, re-elected by `leader()` on death) writes
   `manifest_step_<k>.json` naming every member shard's digests plus the
   oocore cursor. Restore refuses torn/partial manifests (missing
   member, digest mismatch) and falls back to the last fully-committed
   fleet step.
3. **ElasticPlan** — on a death verdict mid-fit: re-derive the chunk
   assignment over the survivors (`ChunkPlanner.remove_hosts` — the
   dead host's unfinished spill-cache chunks become a re-read for the
   inheritors, PR 17's cursor sidecar), re-derive the device mesh over
   the survivors (`mesh()` -> `parallel.data_mesh`), and resume from the
   committed manifest. The shrunk mesh compiles FRESH distributed
   executables through `AotCache` (a new mesh is a new fingerprint —
   recompiles are recorded honestly, never pinned away). Journals
   `elastic.plan` then `elastic.resume` to the RunLedger, ordered after
   the `train.host.dead` verdict that triggered them.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional, Sequence

from ..telemetry import names as tnames
from ..telemetry.spans import get_tracer
from ..utils.checkpoint import CheckpointManager, _fsync_path
from .faults import FaultInjector, InjectedFault
from .metrics import reliability_metrics

logger = logging.getLogger(__name__)


class HostLeases:
    """Observer-local lease table over a shared heartbeat directory.

    Any observed CHANGE in a host's beat row (epoch, stamp, stats)
    renews its lease at `clock()` — by default `time.monotonic`, and
    injectable so tier-1 tests drive expiry without wall sleeps. Driven
    from the supervisor beat like the chunk planner; `check()` never
    raises.

    A verdict is a TRANSITION: the host moves to the dead set once,
    `train.host.dead` fires once (tracer event + run-ledger line), and
    the shared fence is bumped so the dead incarnation's further beats
    raise `FencedOut`. A host that genuinely restarts adopts the bumped
    fence and beats again, but THIS observer's plan has moved on — the
    dead set is sticky for the lifetime of the lease table, matching
    the shrunk plan it actuated.
    """

    def __init__(self, heartbeat, lease_timeout_s: float = 30.0,
                 clock=None, faults: Optional[FaultInjector] = None,
                 metrics=None, tracer=None, ledger=None):
        self.heartbeat = heartbeat
        self.lease_timeout_s = float(lease_timeout_s)
        self.clock = clock if clock is not None else time.monotonic
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self.metrics = metrics if metrics is not None else reliability_metrics
        self._tracer = tracer
        self._ledger = ledger
        self._self = getattr(heartbeat, "process_id", None)
        self._leases: dict = {}       # pid -> (row fingerprint, renewed_at)
        self._dead: set = set()

    # -- queries -------------------------------------------------------------
    @property
    def live(self) -> list:
        return sorted(set(self._leases) - self._dead)

    @property
    def dead(self) -> list:
        return sorted(self._dead)

    # -- the check ------------------------------------------------------------
    @staticmethod
    def _fingerprint(row: dict) -> str:
        return json.dumps({k: v for k, v in row.items() if k != "age_s"},
                          sort_keys=True, default=str)

    def check(self) -> list:
        """One liveness pass; returns the hosts NEWLY declared dead (empty
        on a steady round). Fires the seeded `cluster.lease.expire` site
        once per (round, host) in sorted-host order: kind `expire` forces
        a false-positive verdict on that host (fencing then costs it one
        rejected beat — the chaos contract); kind `error` skips the
        round. Never raises — liveness is driven from the beat path."""
        try:
            rows = {}
            for row in self.heartbeat.read_all():
                try:
                    rows[int(row.get("process_id"))] = row
                except (TypeError, ValueError):
                    continue
        except Exception:  # noqa: BLE001 - a torn directory loses one pass
            return []
        now = self.clock()
        newly = []
        for pid in sorted(set(rows) | set(self._leases)):
            if pid in self._dead:
                continue
            row = rows.get(pid)
            prev = self._leases.get(pid)
            if row is not None:
                fp = self._fingerprint(row)
                if prev is None or prev[0] != fp:
                    prev = (fp, now)       # new content observed: renew
                    self._leases[pid] = prev
            if prev is None:
                continue
            age = now - prev[1]
            forced = None
            if self.faults is not None:
                try:
                    forced = self.faults.perturb("cluster.lease.expire")
                except InjectedFault:
                    return newly           # injected error: skip the round
            expired = age > self.lease_timeout_s or (
                forced is not None and forced.kind == "expire")
            if expired and pid != self._self:
                self._declare_dead(pid, age)
                newly.append(pid)
        self.metrics.set_gauge(tnames.CLUSTER_HOSTS_LIVE, len(self.live))
        self.metrics.set_gauge(tnames.CLUSTER_HOSTS_DEAD, len(self._dead))
        return newly

    def _declare_dead(self, pid: int, age: float) -> None:
        self._dead.add(pid)
        # lazy: parallel.cluster itself imports reliability submodules, so
        # a module-level import here would cycle when cluster loads first
        from ..parallel.cluster import bump_fence
        try:
            # the fence bump IS the verdict's write barrier: from here a
            # beat carrying the old token raises FencedOut
            bump_fence(self.heartbeat.directory, pid)
        except OSError as e:
            logger.warning("fence bump for dead host %d failed (%s: %s)",
                           pid, type(e).__name__, e)
        tracer = self._tracer if self._tracer is not None else get_tracer()
        tracer.event(tnames.TRAIN_HOST_DEAD_EVENT, host=pid,
                     age_s=round(age, 3),
                     lease_timeout_s=self.lease_timeout_s)
        if self._ledger is not None:
            try:
                self._ledger.append_event(
                    tnames.TRAIN_HOST_DEAD_EVENT, host=pid,
                    age_s=round(age, 3),
                    lease_timeout_s=self.lease_timeout_s)
            except Exception:  # noqa: BLE001 - journal, not control
                pass
        logger.warning("host %d declared dead: lease aged %.3fs past "
                       "%.3fs budget", pid, age, self.lease_timeout_s)


def leader(live_hosts: Sequence[int]) -> int:
    """Fleet leader = lowest live process_id; re-election on death is
    just re-evaluating this over the survivor set."""
    hosts = sorted(int(h) for h in live_hosts)
    if not hosts:
        raise ValueError("leader() of an empty host set")
    return hosts[0]


class FleetCheckpoint:
    """Two-phase-commit fleet checkpoint over one shared directory.

        <dir>/host_<pid>/step_<k>/payload.npz+meta.json   (phase 1)
        <dir>/manifest_step_<k>.json                      (phase 2)

    `manager` is this host's shard CheckpointManager — hand it to an
    `AsyncCheckpointWriter` exactly like the single-host path; the shard
    write IS phase 1. `commit()` is leader-only and refuses until every
    live member's step-k shard is on disk with digests; the manifest
    write is atomic (tmp + replace + fsync) and fires the seeded
    `elastic.commit` site between tmp-write and replace, so a leader
    killed mid-commit leaves no manifest at all — the next leader simply
    re-commits. `latest_committed()`/`restore()` verify every member
    digest and fall back past torn or partial manifests.
    """

    def __init__(self, directory: str, process_id: int,
                 max_to_keep: int = 3,
                 faults: Optional[FaultInjector] = None, metrics=None):
        self.directory = directory
        self.process_id = int(process_id)
        os.makedirs(directory, exist_ok=True)
        self.metrics = metrics if metrics is not None else reliability_metrics
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self.manager = CheckpointManager(
            self._host_dir(self.process_id), max_to_keep=max_to_keep)

    def _host_dir(self, pid: int) -> str:
        return os.path.join(self.directory, f"host_{int(pid)}")

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"manifest_step_{int(step)}.json")

    # -- phase 1 ---------------------------------------------------------------
    def save_shard(self, step: int, payload: dict) -> None:
        """This host's step-k shard (digested + fsync'd by the manager).
        Loops that already own an AsyncCheckpointWriter submit to
        `self.manager` through it instead."""
        self.manager.save(int(step), payload)

    def _member_digests(self, pid: int, step: int) -> Optional[dict]:
        """The recorded `_digests` of `pid`'s step-k shard; None when the
        shard is absent or its meta is torn (phase 1 not landed)."""
        try:
            with open(os.path.join(self._host_dir(pid), f"step_{int(step)}",
                                   "meta.json")) as f:
                meta = json.load(f)
            digests = meta.get("_digests")
            if (isinstance(digests, dict) and digests
                    and all(isinstance(v, str) for v in digests.values())):
                return digests
        except (OSError, ValueError):
            pass
        return None

    # -- phase 2 ---------------------------------------------------------------
    def commit(self, step: int, live_hosts: Sequence[int],
               extra: Optional[dict] = None) -> bool:
        """Leader-only manifest write. Returns False (without writing)
        when this host is not the leader of `live_hosts` or when any
        member's step-k shard has not landed yet; True once the manifest
        is durably committed. `extra` rides in the manifest verbatim —
        the oocore staging cursor goes here."""
        hosts = sorted(int(h) for h in live_hosts)
        if not hosts or self.process_id != leader(hosts):
            return False
        members = {}
        for pid in hosts:
            digests = self._member_digests(pid, step)
            if digests is None:
                return False          # phase 1 incomplete: try again later
            members[str(pid)] = digests
        manifest = {"step": int(step), "leader": self.process_id,
                    "hosts": members}
        if extra:
            manifest.update(extra)
        path = self._manifest_path(step)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        if self.faults is not None:
            # a `crash` here is the leader dying mid-commit: the tmp is
            # left behind, no manifest exists, the next leader re-commits
            self.faults.perturb("elastic.commit")
        os.replace(tmp, path)
        _fsync_path(self.directory)
        self.metrics.inc(tnames.ELASTIC_MANIFEST_COMMITS)
        return True

    # -- restore ---------------------------------------------------------------
    def committed_steps(self) -> list:
        steps = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return []
        for name in names:
            if name.startswith("manifest_step_") and name.endswith(".json"):
                try:
                    steps.append(int(name[len("manifest_step_"):-5]))
                except ValueError:
                    continue
        return sorted(steps)

    def _verify_manifest(self, step: int) -> Optional[dict]:
        """Parse + verify one manifest; None when torn or partial (a
        named member shard missing or carrying different digests)."""
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return None
        hosts = manifest.get("hosts")
        if (not isinstance(hosts, dict) or not hosts
                or int(manifest.get("step", -1)) != int(step)):
            return None
        for pid, want in sorted(hosts.items()):
            try:
                got = self._member_digests(int(pid), step)
            except (TypeError, ValueError):
                return None
            if got is None or got != want:
                return None
        return manifest

    def latest_committed(self):
        """(step, manifest) of the newest fully-committed fleet step, or
        None. Torn/partial manifests are counted and skipped — restore
        NEVER lands on a step some member didn't finish."""
        for step in sorted(self.committed_steps(), reverse=True):
            manifest = self._verify_manifest(step)
            if manifest is not None:
                return step, manifest
            self.metrics.inc(tnames.ELASTIC_MANIFEST_REJECTED)
            logger.warning("fleet manifest step %d torn/partial; falling "
                           "back", step)
        return None

    def restore(self, pid: Optional[int] = None):
        """(step, manifest, payload) from the last committed fleet step,
        with `payload` the digest-verified shard of `pid` (default: this
        host); None when no committed step exists."""
        committed = self.latest_committed()
        if committed is None:
            return None
        step, manifest = committed
        who = self.process_id if pid is None else int(pid)
        mgr = self.manager if who == self.process_id else \
            CheckpointManager(self._host_dir(who))
        return step, manifest, mgr.restore(step=step)


class ElasticPlan:
    """Survivor-side shrink-resume: one object that turns a death verdict
    into (a) a re-derived chunk plan, (b) a shrunk device mesh, and (c)
    a resume point from the committed fleet manifest. Journals
    `elastic.plan` on shrink and `elastic.resume` on resume, so the run
    ledger pins `train.host.dead < elastic.plan < elastic.resume`."""

    def __init__(self, planner=None, fleet: Optional[FleetCheckpoint] = None,
                 devices_per_host: int = 1, metrics=None, tracer=None,
                 ledger=None):
        self.planner = planner
        self.fleet = fleet
        self.devices_per_host = max(int(devices_per_host), 1)
        self.metrics = metrics if metrics is not None else reliability_metrics
        self._tracer = tracer
        self._ledger = ledger
        self.survivors: list = [] if planner is None else list(planner.hosts)
        self.restaged: dict = {}

    def _journal(self, event: str, **attrs) -> None:
        tracer = self._tracer if self._tracer is not None else get_tracer()
        tracer.event(event, **attrs)
        if self._ledger is not None:
            try:
                self._ledger.append_event(event, **attrs)
            except Exception:  # noqa: BLE001 - journal, not control
                pass

    def shrink(self, dead: Sequence[int]) -> dict:
        """Re-derive the assignment over the survivors: the dead hosts'
        unfinished chunks drain to the inheritors (`remove_hosts` — a
        re-READ of the shared spill cache, not a recompute) and the dead
        hosts leave the rotation for good. Returns the plan summary it
        journals as `elastic.plan`."""
        dead = sorted(int(h) for h in dead)
        if self.planner is not None:
            self.restaged = dict(self.planner.remove_hosts(dead))
            self.survivors = list(self.planner.hosts)
        else:
            self.survivors = [h for h in self.survivors if h not in dead]
        committed = self.fleet.latest_committed() if self.fleet is not None \
            else None
        plan = {"dead": dead, "survivors": list(self.survivors),
                "restaged": sorted(self.restaged),
                "step": None if committed is None else committed[0]}
        self.metrics.inc(tnames.ELASTIC_SHRINKS)
        self._journal(tnames.ELASTIC_PLAN_EVENT, **plan)
        return plan

    def mesh(self):
        """The shrunk 1-D device mesh over the survivors. A NEW mesh is a
        new `AotCache` fingerprint in the distributed GBDT path, so the
        rebuild compiles fresh executables and records them honestly
        (plan.compiles moves; nothing is pinned)."""
        from ..parallel.mesh import data_mesh
        n = len(self.survivors) * self.devices_per_host
        return data_mesh(n if n else None)

    def resume(self, pid: Optional[int] = None):
        """(step, manifest, payload) from the committed fleet manifest
        (None without one), journaled as `elastic.resume`."""
        out = self.fleet.restore(pid=pid) if self.fleet is not None else None
        step = None if out is None else out[0]
        self.metrics.inc(tnames.ELASTIC_RESUMES)
        self._journal(tnames.ELASTIC_RESUME_EVENT, step=step,
                      survivors=list(self.survivors))
        return out
