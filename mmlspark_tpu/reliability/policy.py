"""Unified retry/backoff/deadline/circuit-breaking primitives.

Role-equivalent to FaultToleranceUtils.retryWithTimeout (reference:
downloader/ModelDownloader.scala:37-64) grown to what a production serving
stack needs: before this module the repo ran three divergent retry loops
(`utils/retry.py`, `io/http.py` advanced handler, cognitive client knobs),
none with jitter, none with an overall deadline — `times × timeout + sleeps`
could silently exceed any caller budget, and synchronized clients retried in
lockstep. One `RetryPolicy` now owns the loop shape; callers keep only their
domain-specific "should this outcome retry" logic.

- `RetryPolicy.attempts()` is the loop: yields `Attempt`s, sleeps jittered
  exponential backoff between them, stops on attempt count, overall
  `deadline`, or an exhausted shared `RetryBudget`.
- `CircuitBreaker` is the closed/open/half-open failure-rate breaker that
  stops hammering a dead dependency (trips recorded in
  `reliability.metrics`).
- `Deadline` propagates one time budget through nested timeouts
  (`deadline.clamp(per_attempt_timeout)`).

Everything takes an injectable `sleep`/`clock` so tests run in microseconds,
and an injectable `rng` so jittered schedules are reproducible under
`reliability.faults.FaultInjector` seeds.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, TypeVar

from .metrics import reliability_metrics
from ..telemetry.names import breaker_trips

T = TypeVar("T")

_INF = float("inf")


class Deadline:
    """Absolute time budget on the monotonic clock; `never()` is infinite."""

    __slots__ = ("_at", "_clock")

    def __init__(self, at: float, clock: Callable[[], float] = time.monotonic):
        self._at = at
        self._clock = clock

    @classmethod
    def after(cls, seconds: Optional[float],
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        if seconds is None:
            return cls(_INF, clock)
        return cls(clock() + seconds, clock)

    @classmethod
    def never(cls) -> "Deadline":
        return cls(_INF)

    def remaining(self) -> float:
        return max(self._at - self._clock(), 0.0) if self._at != _INF else _INF

    def expired(self) -> bool:
        return self._at != _INF and self._clock() >= self._at

    def clamp(self, timeout: Optional[float]) -> Optional[float]:
        """Per-attempt timeout that cannot outlive the overall budget.
        None stays None on an infinite deadline (block freely)."""
        rem = self.remaining()
        if rem == _INF:
            return timeout
        return rem if timeout is None else min(timeout, rem)

    def __repr__(self):
        rem = self.remaining()
        return f"Deadline(remaining={'inf' if rem == _INF else f'{rem:.3f}s'})"


class RetryBudget:
    """Token bucket bounding the RATIO of retries to work: each retry spends
    a token, each success refunds `success_credit`. Shared across calls (and
    threads), it prevents retry storms — under a full outage a fleet with
    per-call retries multiplies load by `max_attempts`; a budget caps the
    multiplier fleet-wide."""

    def __init__(self, tokens: float = 10.0, success_credit: float = 0.1,
                 max_tokens: Optional[float] = None):
        self._max = max_tokens if max_tokens is not None else tokens
        self._tokens = min(tokens, self._max)
        self._credit = success_credit
        self._lock = threading.Lock()

    def can_retry(self) -> bool:
        with self._lock:
            return self._tokens >= 1.0

    def on_retry(self) -> bool:
        """Spend one token; False (no retry) when the bucket is empty."""
        with self._lock:
            if self._tokens < 1.0:
                return False
            self._tokens -= 1.0
            return True

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self._credit, self._max)

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class Attempt:
    """One iteration of a RetryPolicy loop. The caller runs its work, then
    either returns/breaks (done) or calls `retry()` — optionally with an
    explicit delay (e.g. a 429 Retry-After) — to request another attempt."""

    __slots__ = ("index", "is_last", "deadline", "_retry", "_delay")

    def __init__(self, index: int, is_last: bool, deadline: Deadline):
        self.index = index
        self.is_last = is_last
        self.deadline = deadline
        self._retry = False
        self._delay: Optional[float] = None

    def retry(self, delay: Optional[float] = None) -> None:
        self._retry = True
        self._delay = delay

    def timeout(self, per_attempt: Optional[float]) -> Optional[float]:
        """Per-attempt timeout clamped to the policy's overall deadline."""
        return self.deadline.clamp(per_attempt)


class RetryPolicy:
    """Jittered-exponential-backoff retry loop with an overall deadline and
    an optional shared retry budget.

    The one loop shape every retry path consumes (utils.retry,
    io.http.advanced_handler, cognitive.base):

        for attempt in policy.attempts():
            try:
                resp = do_work(timeout=attempt.timeout(60.0))
            except TransientError:
                attempt.retry()
                continue
            if resp.throttled and not attempt.is_last:
                attempt.retry(delay=resp.retry_after)
                continue
            return resp
        # attempts/deadline/budget exhausted
    """

    def __init__(self, max_attempts: int = 3, backoff: float = 0.1,
                 backoff_factor: float = 2.0, max_backoff: float = 30.0,
                 jitter: float = 0.1, deadline: Optional[float] = None,
                 retry_on: tuple = (Exception,),
                 budget: Optional[RetryBudget] = None,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, metric_name: str = "retry.retries"):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter is a fraction in [0, 1]")
        self.max_attempts = max_attempts
        self.backoff = backoff
        self.backoff_factor = backoff_factor
        self.max_backoff = max_backoff
        self.jitter = jitter
        self.deadline = deadline
        self.retry_on = retry_on
        self.budget = budget
        self._rng = rng
        self._sleep = sleep
        self._clock = clock
        self._metrics = metrics if metrics is not None else reliability_metrics
        self._metric_name = metric_name

    # -- schedule ------------------------------------------------------------
    def delay_for(self, attempt_index: int) -> float:
        """Backoff before attempt `attempt_index + 1`, jittered ±jitter."""
        base = min(self.backoff * (self.backoff_factor ** attempt_index),
                   self.max_backoff)
        if self.jitter:
            rng = self._rng if self._rng is not None else random
            base *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(base, 0.0)

    def _exhausted(self, index: int, deadline: Deadline) -> bool:
        if index + 1 >= self.max_attempts or deadline.expired():
            return True
        return self.budget is not None and not self.budget.can_retry()

    def attempts(self):
        deadline = Deadline.after(self.deadline, self._clock)
        index = 0
        while True:
            att = Attempt(index, self._exhausted(index, deadline), deadline)
            yield att
            if not att._retry or att.is_last:
                return
            if self.budget is not None and not self.budget.on_retry():
                return
            delay = att._delay if att._delay is not None \
                else self.delay_for(index)
            delay = min(delay, deadline.remaining())
            if delay > 0:
                self._sleep(delay)
            if deadline.expired():
                return
            self._metrics.inc(self._metric_name)
            index += 1

    # -- plain-exception convenience -----------------------------------------
    def call(self, fn: Callable[[], T], retry_on: Optional[tuple] = None,
             on_retry: Optional[Callable] = None) -> T:
        """Run fn() under the policy, retrying on `retry_on` exceptions.
        Raises the last error when the policy is exhausted."""
        retry_on = retry_on if retry_on is not None else self.retry_on
        last: Optional[BaseException] = None
        for att in self.attempts():
            try:
                out = fn()
            except retry_on as e:  # noqa: PERF203 - retry loop by design
                last = e
                if on_retry is not None:
                    on_retry(att, e)
                att.retry()
                continue
            if self.budget is not None:
                self.budget.on_success()
            return out
        assert last is not None
        raise last


class CircuitOpenError(RuntimeError):
    """Raised by CircuitBreaker.call when the circuit is open."""


class CircuitBreaker:
    """Closed/open/half-open breaker over a sliding outcome window.

    Trips OPEN when the last `window` outcomes hold at least
    `failure_threshold` failures AND the failure fraction reaches
    `failure_rate`. After `reset_timeout` seconds one half-open probe is
    allowed: success closes the circuit, failure re-opens it. Trips are
    counted in `reliability.metrics` under `<name>.trips`."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5, failure_rate: float = 0.5,
                 window: int = 20, reset_timeout: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 metrics=None, name: str = "breaker"):
        self.failure_threshold = failure_threshold
        self.failure_rate = failure_rate
        self.window = window
        self.reset_timeout = reset_timeout
        self.name = name
        self._clock = clock
        self._metrics = metrics if metrics is not None else reliability_metrics
        self._lock = threading.Lock()
        self._outcomes: list = []   # rolling 0/1 failure flags, len<=window
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_timeout):
            self._state = self.HALF_OPEN
            self._probing = False
        return self._state

    def allow(self) -> bool:
        """May a call proceed right now? Half-open admits ONE probe."""
        with self._lock:
            state = self._state_locked()
            if state == self.CLOSED:
                return True
            if state == self.HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            if self._state_locked() == self.HALF_OPEN:
                self._state = self.CLOSED
                self._outcomes.clear()
                self._probing = False
                return
            self._push(0)

    def record_failure(self) -> None:
        with self._lock:
            state = self._state_locked()
            if state == self.HALF_OPEN:
                self._trip()
                return
            if state == self.OPEN:
                return
            self._push(1)
            fails = sum(self._outcomes)
            if (fails >= self.failure_threshold
                    and fails / len(self._outcomes) >= self.failure_rate):
                self._trip()

    def _push(self, outcome: int) -> None:
        self._outcomes.append(outcome)
        if len(self._outcomes) > self.window:
            del self._outcomes[0]

    def _trip(self) -> None:
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._probing = False
        self._outcomes.clear()
        self._metrics.inc(breaker_trips(self.name))

    def call(self, fn: Callable[[], T]) -> T:
        """Gate fn() through the breaker: CircuitOpenError without calling
        when open; outcomes recorded otherwise."""
        if not self.allow():
            raise CircuitOpenError(
                f"circuit {self.name!r} is {self.state}")
        try:
            out = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
