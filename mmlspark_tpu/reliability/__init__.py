"""Unified resilience layer: retry/backoff/deadline policies, circuit
breaking, recovery metrics, and a deterministic fault-injection harness
(reference analog: FaultToleranceUtils + the scenario-level fault tests of
HTTPv2Suite, unified and made seed-reproducible). See docs/reliability.md."""
from .elastic import ElasticPlan, FleetCheckpoint, HostLeases, leader
from .faults import (FAULTS_ENV, Fault, FaultInjector, InjectedCrash,
                     InjectedFault)
from .metrics import Counter, Histogram, MetricsRegistry, reliability_metrics
from .policy import (Attempt, CircuitBreaker, CircuitOpenError, Deadline,
                     RetryBudget, RetryPolicy)
from .supervisor import (AsyncCheckpointWriter, Preempted, StepTimeout,
                         TrainingSupervisor)

__all__ = ["RetryPolicy", "RetryBudget", "Attempt", "CircuitBreaker",
           "CircuitOpenError", "Deadline",
           "FaultInjector", "Fault", "InjectedFault", "InjectedCrash",
           "FAULTS_ENV",
           "MetricsRegistry", "Counter", "Histogram", "reliability_metrics",
           "TrainingSupervisor", "AsyncCheckpointWriter", "Preempted",
           "StepTimeout",
           "HostLeases", "FleetCheckpoint", "ElasticPlan", "leader"]
