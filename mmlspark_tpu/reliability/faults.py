"""Deterministic, seedable fault injection.

The reference proves its recovery paths with scenario tests (HTTPv2Suite
fault tolerance :329, flaky connection :401) but each scenario hand-rolls
its own failure; nothing is reproducible from a seed. `FaultInjector` makes
every injected failure — delays, connection resets, worker crashes,
malformed payloads, checkpoint corruption — come from one seeded schedule,
so a chaos test that fails prints a seed that replays the identical fault
sequence.

Design:
- Injection *sites* are names ("serving.worker", "serving.ingress",
  "fuzz.http", ...). Every `fire(site)` call increments a per-site counter;
  rules match by site glob and fire either at fixed per-site call indices
  (`"at": [2, 5]`) or with a seeded per-site probability (`"prob": 0.1`).
- Per-site RNG streams are derived as `crc32(site) ^ seed` — NOT Python's
  randomized `hash()` — so the schedule is stable across processes and
  independent of the order other sites are exercised (thread-safe
  determinism: concurrent sites never perturb each other's stream).
- `history` records every fired fault as `(site, call_index, kind)`; two
  runs with the same seed and the same per-site call sequences produce
  identical histories — that equality IS the reproducibility assertion.
- Zero overhead when disabled: production code holds `None` (the
  `from_env()` default without the env var) and branches on `is not None`;
  no injector object, no call, no lock.

Activation: pass an injector explicitly, or export
`MMLSPARK_TPU_FAULTS='{"seed": 7, "rules": [{"site": "serving.worker",
"kind": "crash", "at": [1]}]}'` and every `FaultInjector.from_env()` site
picks it up.
"""
from __future__ import annotations

import fnmatch
import json
import os
import random
import threading
import time
import zlib
from typing import Callable, NamedTuple, Optional
from ..telemetry.names import FAULT_INJECTED_EVENT

FAULTS_ENV = "MMLSPARK_TPU_FAULTS"

# Hard cap on injected delays: chaos suites must stay fast and the tier-1
# run deterministic-ish under load (ISSUE: no sleeps > 0.2s).
MAX_INJECTED_DELAY = 0.2


class InjectedFault(Exception):
    """A recoverable injected failure (retry/replay paths absorb it)."""


class InjectedCrash(InjectedFault):
    """An injected worker DEATH: escapes the worker's recovery catch so the
    thread actually dies and the watchdog/replay machinery must engage."""


class Fault(NamedTuple):
    site: str
    index: int          # per-site call index the fault fired at
    kind: str           # crash | error | delay | reset | corrupt | ...
    param: Optional[float] = None


class FaultInjector:
    """Seeded rule-driven fault source. See module docstring for the rule
    shapes; unknown kinds are returned to the caller to interpret (serving
    handles "reset", checkpoint tests handle "corrupt", ...)."""

    def __init__(self, seed: int = 0, rules: Optional[list] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.seed = int(seed)
        self.rules = list(rules or [])
        for r in self.rules:
            if "site" not in r or "kind" not in r:
                raise ValueError(f"fault rule needs site+kind: {r!r}")
        self._sleep = sleep
        self._lock = threading.Lock()
        self._counts: dict = {}
        self._rngs: dict = {}
        self.history: list = []   # list[Fault], in fire order

    @classmethod
    def from_env(cls, var: str = FAULTS_ENV) -> Optional["FaultInjector"]:
        """Build from the env var's JSON spec; None when unset (the
        zero-overhead disabled state)."""
        spec = os.environ.get(var)
        if not spec:
            return None
        cfg = json.loads(spec)
        return cls(seed=cfg.get("seed", 0), rules=cfg.get("rules", []))

    # -- deterministic per-site randomness ------------------------------------
    def _site_rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            # crc32, not hash(): stable across processes/PYTHONHASHSEED
            rng = random.Random(zlib.crc32(site.encode()) ^ self.seed)
            self._rngs[site] = rng
        return rng

    # -- telemetry -------------------------------------------------------------
    def _emit(self, fault: Fault) -> None:
        """Structured telemetry event per fired fault: a chaos run's event
        log then interleaves injections with the recovery they provoked
        (supervisor restarts, replayed epochs) in causal (seq) order.
        Lazy import + exception guard: observability must never change a
        fault schedule's behavior."""
        try:
            from ..telemetry.spans import get_tracer
            get_tracer().event(FAULT_INJECTED_EVENT, site=fault.site,
                               index=fault.index, kind=fault.kind)
        except Exception:  # noqa: BLE001
            pass

    # -- core ------------------------------------------------------------------
    def fire(self, site: str) -> Optional[Fault]:
        """Advance the site's call counter and return the fault scheduled
        for this call, if any. First matching rule wins."""
        fault = None
        with self._lock:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
            for rule in self.rules:
                if not fnmatch.fnmatchcase(site, rule["site"]):
                    continue
                at = rule.get("at")
                if at is not None:
                    if index not in at:
                        continue
                elif self._site_rng(site).random() >= rule.get("prob", 0.0):
                    continue
                fault = Fault(site, index, rule["kind"], rule.get("param"))
                self.history.append(fault)
                break
        if fault is not None:
            self._emit(fault)
        return fault

    def perturb(self, site: str) -> Optional[Fault]:
        """fire() plus the generic kinds applied in place: "delay" sleeps
        (capped), "error" raises InjectedFault, "crash" raises
        InjectedCrash. Site-specific kinds are returned for the caller."""
        fault = self.fire(site)
        if fault is None:
            return None
        if fault.kind == "delay":
            self._sleep(min(fault.param or 0.05, MAX_INJECTED_DELAY))
            return fault
        if fault.kind == "crash":
            raise InjectedCrash(f"injected crash at {site}#{fault.index}")
        if fault.kind == "error":
            raise InjectedFault(f"injected error at {site}#{fault.index}")
        return fault

    def wrap(self, site: str, fn: Callable) -> Callable:
        """Callable wrapper: perturb(site) before each call of fn."""
        def wrapped(*args, **kwargs):
            self.perturb(site)
            return fn(*args, **kwargs)
        return wrapped

    # -- payload/file corruption ----------------------------------------------
    CORRUPT_MODES = ("truncate", "flip", "garbage")

    def corrupt_bytes(self, site: str, data: bytes) -> bytes:
        """Deterministically mangle a payload (malformed/truncated bytes for
        fuzzing): truncate at a seeded point, flip seeded bytes, or splice
        seeded garbage. Unconditional — callers decide when; the mode and
        positions come from the site's seeded stream."""
        with self._lock:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
            rng = self._site_rng(site)
            mode = rng.choice(self.CORRUPT_MODES)
            fault = Fault(site, index, f"corrupt:{mode}")
            self.history.append(fault)
            if not data:
                out_bytes = data
            elif mode == "truncate":
                out_bytes = data[: rng.randrange(len(data))]
            elif mode == "flip":
                out = bytearray(data)
                for _ in range(max(1, len(out) // 16)):
                    pos = rng.randrange(len(out))
                    out[pos] ^= 1 + rng.randrange(255)
                out_bytes = bytes(out)
            else:
                junk = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(1, 9)))
                pos = rng.randrange(len(data) + 1)
                out_bytes = data[:pos] + junk + data[pos:]
        self._emit(fault)
        return out_bytes

    def corrupt_file(self, path: str, site: str = "checkpoint") -> None:
        """Truncate a file to a seeded fraction of its size — the
        checkpoint-corruption fault (a crash mid-write of a non-atomic
        copy, a torn disk)."""
        size = os.path.getsize(path)
        with self._lock:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
            keep = self._site_rng(site).randrange(max(size, 1))
            fault = Fault(site, index, "corrupt:truncate-file", float(keep))
            self.history.append(fault)
        self._emit(fault)
        with open(path, "rb+") as f:
            f.truncate(keep)

    # -- introspection ---------------------------------------------------------
    def schedule(self) -> list:
        """(site, index, kind) triples of every fired fault — compare across
        runs to assert seed-reproducibility."""
        return [(f.site, f.index, f.kind) for f in self.history]

    def __repr__(self):
        return (f"FaultInjector(seed={self.seed}, rules={len(self.rules)}, "
                f"fired={len(self.history)})")
