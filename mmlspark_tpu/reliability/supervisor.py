"""Fault-tolerant training supervision: async verified checkpoints,
preemption handling, deterministic crash-resume.

The reference wraps every long-running LightGBM training phase in
`FaultToleranceUtils.retryWithTimeout` and resumes multi-batch fits from
serialized model strings (SURVEY §2.10, §5); our training loops previously
died unrecoverably on a worker crash, a host preemption, or a torn
checkpoint. `TrainingSupervisor` wraps ANY step-function training loop and
provides the four guarantees the ISSUE demands:

1. **Async checkpointing** — `snapshot_fn()` runs on the step thread (a
   cheap host copy of params/opt-state), the npz/meta write happens on a
   background `AsyncCheckpointWriter` thread behind a BOUNDED latest-wins
   queue, so the hot loop never blocks on disk. Instrumented as
   `checkpoint.write.{pending,coalesced,errors}` + the
   `checkpoint.{submit,snapshot,write}` latency histograms.
2. **Integrity** — writes go through `utils.checkpoint.CheckpointManager`,
   which records per-file SHA-256 digests at save and verifies them on
   restore, so resume skips silently-corrupted steps (not just truncated
   ones) to the next-newest valid step.
3. **Crash/preemption handling** — SIGTERM/SIGINT set a flag; the loop
   finishes the in-flight step, writes a final SYNCHRONOUS checkpoint, and
   raises `Preempted` (catch it and `sys.exit(0)` for the clean exit code a
   preempting scheduler expects, or pass `exit_on_preempt=True`). A
   `step_timeout` wall-clock budget per step raises `StepTimeout`; failed
   steps (`restart_on`, by default injected faults + timeouts) restart from
   the last in-memory snapshot under a `reliability.RetryPolicy`. Fault
   sites `train.step<k>`, `train.ckpt.write`, and `train.ckpt.read` make
   every failure mode seed-reproducible.
4. **Deterministic resume** — the payload rides the data cursor (the step
   index) and the per-step results history next to the model state, so a
   killed-and-resumed run replays the remaining steps on bit-identical
   state and produces bit-identical params/losses to an uninterrupted run
   (pinned by tests/test_supervisor.py).

Consumers: `ShardedLMTrainer.run_stream(checkpoint_dir=...)` and the GBDT
estimators' `checkpoint_dir` path (which reuses `AsyncCheckpointWriter`
directly — the boosting loop owns its own chunk cadence). See
docs/reliability.md "Fault-tolerant training".
"""
from __future__ import annotations

import collections
import json
import logging
import signal as _signal
import threading
import time
from typing import Callable, Optional, Sequence

from ..telemetry.spans import get_tracer
from ..telemetry import names as tnames
from ..utils.checkpoint import CheckpointManager
from ..utils.tracing import annotate as _annotate
from .faults import FaultInjector, InjectedFault
from .metrics import reliability_metrics
from .policy import RetryPolicy

logger = logging.getLogger(__name__)

# Reserved payload keys the supervisor rides alongside the user's state.
STEP_KEY = "sup_step"
RESULTS_KEY = "sup_results"
PREEMPTED_KEY = "sup_preempted"
CLOCK_KEY = "sup_clock"          # StepClock accounting (goodput survives kill)
_RESERVED = (STEP_KEY, RESULTS_KEY, PREEMPTED_KEY, CLOCK_KEY)


class StepTimeout(RuntimeError):
    """A training step exceeded its wall-clock budget (`step_timeout`)."""


class Preempted(RuntimeError):
    """Raised by `TrainingSupervisor.run` after SIGTERM/SIGINT triggered the
    final synchronous checkpoint. The run is resumable from that checkpoint;
    catch this and `sys.exit(0)` so the scheduler sees a clean exit."""

    def __init__(self, step: int, signum: int):
        super().__init__(f"preempted by signal {signum} at step {step} "
                         f"(final checkpoint written)")
        self.step = step
        self.signum = signum


class AsyncCheckpointWriter:
    """Background checkpoint writer behind a bounded latest-wins queue.

    `submit()` NEVER blocks the calling (step) thread: when the queue is
    full the OLDEST pending snapshot is dropped (the newest state supersedes
    it — counted under `checkpoint.write.coalesced`) and the new one is
    enqueued. A failed async write is logged and counted
    (`checkpoint.write.errors`) but does not kill training — a torn write
    costs one checkpoint interval, exactly like a torn disk would.
    `write_sync()` drains the queue then writes on the caller's thread (the
    final/preemption checkpoint, which MUST be durable before exit).
    """

    def __init__(self, manager: CheckpointManager, depth: int = 2,
                 metrics=None, faults: Optional[FaultInjector] = None):
        self.manager = manager
        self.depth = max(int(depth), 1)
        self.metrics = metrics if metrics is not None else reliability_metrics
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self._q: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._busy = False
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    # -- producer side (step thread) -----------------------------------------
    def submit(self, step: int, payload: dict,
               prune_newer: bool = False) -> None:
        t0 = time.perf_counter()
        with self._cond:
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            while len(self._q) >= self.depth:
                self._q.popleft()
                self.metrics.inc(tnames.CHECKPOINT_WRITE_COALESCED)
            self._q.append((int(step), payload, bool(prune_newer)))
            self.metrics.set_gauge(tnames.CHECKPOINT_WRITE_PENDING, len(self._q))
            if self._thread is None:
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True,
                                                name="ckpt-writer")
                self._thread.start()
            self._cond.notify_all()
        self.metrics.observe_ms(tnames.CHECKPOINT_SUBMIT,
                                (time.perf_counter() - t0) * 1000.0)

    def pending(self) -> int:
        with self._cond:
            return len(self._q) + (1 if self._busy else 0)

    def flush(self, timeout: float = 30.0) -> None:
        """Block until every submitted snapshot has been written."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._q or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"checkpoint writer did not drain within {timeout}s "
                        f"({len(self._q)} pending)")
                self._cond.wait(remaining)

    def write_sync(self, step: int, payload: dict,
                   prune_newer: bool = False,
                   flush_timeout: float = 30.0) -> None:
        """Drain pending async writes, then write THIS snapshot on the
        caller's thread — the final checkpoint must be on disk when this
        returns, so errors propagate instead of being absorbed."""
        self.flush(timeout=flush_timeout)
        self._write(int(step), payload, bool(prune_newer), absorb=False)

    def close(self, flush: bool = True) -> None:
        if flush:
            try:
                self.flush()
            except TimeoutError:
                logger.warning("checkpoint writer close(): flush timed out")
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- writer thread --------------------------------------------------------
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait()
                if not self._q and self._closed:
                    return
                step, payload, prune = self._q.popleft()
                self._busy = True
                self.metrics.set_gauge(tnames.CHECKPOINT_WRITE_PENDING,
                                       len(self._q))
            try:
                self._write(step, payload, prune, absorb=True)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def _write(self, step: int, payload: dict, prune_newer: bool,
               absorb: bool) -> None:
        t0 = time.perf_counter()
        # lifecycle span (sync finals + async writer-thread writes alike):
        # chaos/telemetry runs see every write attempt with its outcome
        span = get_tracer().start_span(
            tnames.CHECKPOINT_WRITE_SPAN,
            attrs={"step": step, "sync": not absorb})
        try:
            if self.faults is not None:
                self.faults.perturb("train.ckpt.write")
            self.manager.save(step, payload, prune_newer=prune_newer)
            if span is not None:
                span.finish(ok=True)
        except Exception as e:  # noqa: BLE001 - async writes must not kill training
            if span is not None:
                span.finish(ok=False, error=type(e).__name__)
            self.metrics.inc(tnames.CHECKPOINT_WRITE_ERRORS)
            logger.warning("checkpoint write for step %d failed (%s: %s)",
                           step, type(e).__name__, e)
            if not absorb:
                raise
        finally:
            self.metrics.observe_ms(tnames.CHECKPOINT_WRITE,
                                    (time.perf_counter() - t0) * 1000.0)


class TrainingSupervisor:
    """Wrap a step-function training loop with checkpoint/resume, restart,
    and preemption handling.

        sup = TrainingSupervisor(ckpt_dir, snapshot_fn, restore_fn,
                                 checkpoint_every=10)
        losses = sup.run(step_fn, n_steps)   # resumes, restarts, finalizes

    - `snapshot_fn() -> dict`: the training state as a CheckpointManager
      payload (numpy arrays + JSON scalars). Called on the step thread —
      keep it a cheap host copy; the disk write happens on the writer
      thread. RNG state and any data-cursor state beyond the step index
      must ride in this payload for resume to be deterministic.
    - `restore_fn(payload) -> None`: apply a payload back onto live state.
    - `step_fn(step) -> result`: one training step; results are collected
      (and, when JSON-serializable, checkpointed so a resumed run returns
      the full history).
    - `seek(step)` (optional, per-`run`): position the data stream at
      `step` — called once after resume and again after every crash rewind.

    Restart policy: exceptions in `restart_on` (default: injected faults
    and step timeouts) restore the last in-memory snapshot and replay from
    its step; `retry_policy` bounds TOTAL restarts per run (jittered
    backoff between them). Anything else propagates — the on-disk
    checkpoints then make the NEXT process's `run()` resume.
    """

    def __init__(self, directory: str,
                 snapshot_fn: Callable[[], dict],
                 restore_fn: Callable[[dict], None], *,
                 checkpoint_every: int = 1, max_to_keep: int = 3,
                 queue_depth: int = 2,
                 step_timeout: Optional[float] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 restart_on: Sequence[type] = (InjectedFault, StepTimeout),
                 handle_signals: bool = True,
                 heartbeat=None,
                 manager: Optional[CheckpointManager] = None,
                 metrics=None, faults: Optional[FaultInjector] = None,
                 step_clock=None, straggler=None,
                 straggler_threshold: float = 1.5,
                 chunk_planner=None, host_leases=None, elastic=None):
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.checkpoint_every = max(int(checkpoint_every), 0)  # 0 = final only
        self.step_timeout = step_timeout
        self.restart_on = tuple(restart_on)
        self.handle_signals = handle_signals
        self.heartbeat = heartbeat
        self.metrics = metrics if metrics is not None else reliability_metrics
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self.manager = manager if manager is not None else CheckpointManager(
            directory, max_to_keep=max_to_keep)
        self.retry_policy = retry_policy if retry_policy is not None else \
            RetryPolicy(max_attempts=3, backoff=0.05, max_backoff=1.0,
                        metric_name=tnames.TRAIN_STEP_RETRIES)
        self.writer = AsyncCheckpointWriter(self.manager, depth=queue_depth,
                                            metrics=self.metrics,
                                            faults=self.faults)
        # goodput/MFU accounting (telemetry/goodput.py): the clock rides
        # every step; its state rides the checkpoint payload so a
        # killed-and-resumed run keeps cumulative goodput. Lazy import —
        # this module is imported by the reliability package init.
        from ..telemetry.goodput import StepClock, StragglerDetector
        self.clock = (step_clock if step_clock is not None
                      else StepClock(registry=self.metrics))
        if straggler is None and heartbeat is not None:
            # multi-host runs exchange per-host step p50s through the
            # heartbeat files; every host runs the same check on its beat
            straggler = StragglerDetector(heartbeat,
                                          threshold=straggler_threshold,
                                          registry=self.metrics)
        self.straggler = straggler or None
        # straggler ACTUATION (data/planner.py): flagged hosts from the
        # detector's beat-time check drain their pending out-of-core
        # chunks to healthy peers; detection stays pure observability
        # when no planner is handed in
        self.chunk_planner = chunk_planner
        # lease-based liveness (reliability/elastic.py): the beat drives
        # the observer-local death check, and a verdict actuates the
        # elastic shrink (or, without an ElasticPlan, just drains the
        # dead hosts' chunks off the plan)
        self.host_leases = host_leases
        self.elastic = elastic
        self.resumed_step: Optional[int] = None
        self._resumed_results: list = []
        self._last: Optional[tuple] = None   # (step, payload, results) rewind
        self._preempt: Optional[int] = None
        self._att_gen = None
        self._att = None
        self._results_numeric = True    # losses ride the binary payload
        self._results_jsonable = True   # flips once a non-JSON result shows
        self._results_probed = 0        # results proven serializable so far

    # -- resume ---------------------------------------------------------------
    def resume(self) -> int:
        """Restore the newest digest-valid checkpoint (if any) through
        `restore_fn` and return the step to continue from (0 = fresh run).
        Fires the `train.ckpt.read` fault site."""
        if self.faults is not None:
            self.faults.perturb("train.ckpt.read")
        if self.manager.latest_step() is None:
            return 0
        payload, loaded = self.manager.restore(with_step=True)
        # default to the step ACTUALLY loaded (a corrupt-newest fallback
        # makes it differ from latest_step(); seeking the data cursor past
        # state that never trained would silently skip batches)
        step = int(payload.get(STEP_KEY, loaded))
        clock_state = payload.get(CLOCK_KEY)
        if clock_state is not None:
            # cumulative goodput spans the kill: the resumed run keeps
            # the prior run's wall/lost accounting instead of reset-to-1
            self.clock.restore_state(clock_state)
        hist = payload.get(RESULTS_KEY, ())
        import numpy as np
        if isinstance(hist, np.ndarray):   # numeric history rode the npz
            hist = [float(v) for v in hist]
        self._resumed_results = list(hist if hist is not None else ())
        self.restore_fn({k: v for k, v in payload.items()
                         if k not in _RESERVED})
        self.resumed_step = step
        self.metrics.inc(tnames.TRAIN_RESUMES)
        self.metrics.set_gauge(tnames.TRAIN_RESUME_STEP, step)
        get_tracer().event(tnames.TRAIN_RESUME_EVENT, step=step)
        logger.info("resumed training from checkpoint step %d", step)
        return step

    # -- the loop -------------------------------------------------------------
    def run(self, step_fn: Callable[[int], object], n_steps: int, *,
            seek: Optional[Callable[[int], None]] = None,
            resume: bool = True, exit_on_preempt: bool = False) -> list:
        start = self.resume() if resume else 0
        results = list(self._resumed_results)
        del results[start:]   # history beyond the restored step is stale
        if start >= n_steps:
            # the restored state is AT (or past) the requested horizon:
            # nothing to run, and rewriting a final checkpoint at n_steps
            # would understate the state the newer step dirs still hold
            logger.warning(
                "resumed checkpoint step %d >= n_steps %d; returning the "
                "restored history without training", start, n_steps)
            return results
        step = start
        self._mark(step, results, write=False)   # in-memory rewind baseline
        if seek is not None:
            seek(step)
        old_handlers = self._install_signals()
        try:
            while step < n_steps:
                if self._preempt is not None:
                    self._finalize(step, results, preempted=True)
                    if exit_on_preempt:
                        raise SystemExit(0)
                    raise Preempted(step, self._preempt)
                try:
                    # step span: covers the fault site too, so an injected
                    # step failure records error=<type> on ITS step before
                    # the restart machinery engages. The clock wraps both:
                    # a failed attempt's wall books as lost.
                    with self.clock.step(step), \
                            get_tracer().span(tnames.TRAIN_STEP_SPAN,
                                              step=step):
                        if self.faults is not None:
                            t_fault = time.perf_counter()
                            fault = self.faults.perturb(f"train.step{step}")
                            if fault is not None and fault.kind == "delay":
                                # an injected stall models an external
                                # pause (preemption, contention): wall
                                # that produced no state — lost time in
                                # the goodput account
                                self.clock.note(
                                    "lost",
                                    time.perf_counter() - t_fault)
                        # `train.step` region (telemetry/profiler.py):
                        # a TraceAnnotation on captured profiles plus a
                        # host-wall note into the roofline ledger, so
                        # triggered captures attribute device time to
                        # the step and roofline.json carries a
                        # train.step row on every backend
                        with _annotate("train.step"):
                            out = self._call_step(step_fn, step)
                except self.restart_on as e:
                    step, results = self._restart(e, seek)
                    continue
                results.append(out)
                step += 1
                if (self.checkpoint_every and step < n_steps
                        and step % self.checkpoint_every == 0):
                    self._mark(step, results, write=True)
            if self._preempt is not None:
                # the signal landed DURING the last step: it must not be
                # silently swallowed by a clean finish — the scheduler
                # expects the process to exit
                self._finalize(step, results, preempted=True)
                if exit_on_preempt:
                    raise SystemExit(0)
                raise Preempted(step, self._preempt)
            self._finalize(n_steps, results, preempted=False)
            return results
        finally:
            self._restore_signals(old_handlers)

    def close(self) -> None:
        self.writer.close(flush=True)

    @property
    def preempted(self) -> bool:
        return self._preempt is not None

    # -- internals ------------------------------------------------------------
    def _call_step(self, step_fn, step: int):
        if self.step_timeout is None:
            return step_fn(step)
        box: dict = {}

        def target():
            try:
                box["out"] = step_fn(step)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                box["err"] = e

        t = threading.Thread(target=target, daemon=True,
                             name=f"train-step-{step}")
        t.start()
        t.join(self.step_timeout)
        if t.is_alive():
            # The stuck step thread is ABANDONED (daemon) and the retried
            # step runs fresh. Caveat: if the hung step later unblocks and
            # mutates shared trainer state it races the replay — the
            # timeout watchdog suits steps that hang in host I/O and die
            # with the process (a truly wedged collective, a dead NFS
            # mount), not steps that may eventually complete.
            self.metrics.inc(tnames.TRAIN_STEP_TIMEOUTS)
            raise StepTimeout(
                f"step {step} exceeded its {self.step_timeout}s budget")
        if "err" in box:
            raise box["err"]
        return box.get("out")

    def _restart(self, err: BaseException, seek) -> tuple:
        if self._att_gen is None:
            self._att_gen = self.retry_policy.attempts()
            self._att = next(self._att_gen)
        if self._att.is_last:
            raise err
        self._att.retry()
        self._att = next(self._att_gen, None)
        if self._att is None:
            raise err
        assert self._last is not None
        last_step, payload, results = self._last
        # everything since that snapshot re-executes: its wall is lost
        self.clock.rewound()
        self.metrics.inc(tnames.TRAIN_STEP_RESTARTS)
        get_tracer().event(tnames.TRAIN_RESTART_EVENT, step=last_step,
                           error=type(err).__name__)
        logger.warning("training step failed (%s: %s); restarting from "
                       "snapshot step %d", type(err).__name__, err, last_step)
        self.restore_fn({k: v for k, v in payload.items()
                         if k not in _RESERVED})
        if seek is not None:
            seek(last_step)
        # rewind from the IN-MEMORY history, not the payload: non-JSON
        # results never ride the payload, and an in-process restart must
        # not discard them (only a cross-process resume legitimately does)
        return last_step, list(results)

    def _snapshot(self, step: int, results: list) -> dict:
        import numpy as np
        t0 = time.perf_counter()
        payload = dict(self.snapshot_fn())
        for k in _RESERVED:
            payload.pop(k, None)
        payload[STEP_KEY] = int(step)
        payload[CLOCK_KEY] = np.asarray(self.clock.state_vector(),
                                        np.float64)
        if self._results_numeric and all(
                isinstance(r, (int, float, np.floating, np.integer))
                for r in results[self._results_probed:]):
            # the common case (per-step losses): the history rides the
            # BINARY payload — no O(history) json text per checkpoint
            self._results_probed = len(results)
            payload[RESULTS_KEY] = np.asarray(results, np.float64)
        else:
            self._results_numeric = False
            if self._results_jsonable:
                try:
                    # probe only results not yet proven serializable — the
                    # snapshot stays O(new results) per mark
                    json.dumps(results[self._results_probed:])
                    self._results_probed = len(results)
                    payload[RESULTS_KEY] = list(results)
                except (TypeError, ValueError):
                    # non-JSON results: resumable, but history restarts
                    self._results_jsonable = False
        self.metrics.observe_ms(tnames.CHECKPOINT_SNAPSHOT,
                                (time.perf_counter() - t0) * 1000.0)
        return payload

    def _beat(self, step: Optional[int]) -> None:
        """Heartbeat write (or clear, step=None) — an observability aid: a
        lost beat (injected fault, NFS blip, disk full) is counted and
        logged, never allowed to kill a healthy training loop."""
        if self.heartbeat is None:
            return
        try:
            if step is None:
                self.heartbeat.clear()
            else:
                # the beat carries this host's windowed step p50 so
                # every peer's straggler check sees it
                self.heartbeat.beat(step, stats=self.clock.beat_stats())
        except Exception as e:  # noqa: BLE001 - observability must not kill
            self.metrics.inc(tnames.CLUSTER_HEARTBEAT_ERRORS)
            logger.warning("heartbeat update failed (%s: %s)",
                           type(e).__name__, e)
        if step is not None and self.straggler is not None:
            flagged = self.straggler.check()   # never raises (observability)
            if flagged and self.chunk_planner is not None:
                # actuation: drain the flagged hosts' pending chunks
                # (ordered AFTER the train.straggler event the check just
                # emitted). Re-planning failure must not kill training —
                # the straggler then simply keeps its chunks.
                try:
                    self.chunk_planner.reassign(flagged)
                except Exception as e:  # noqa: BLE001
                    logger.warning("chunk reassignment failed (%s: %s)",
                                   type(e).__name__, e)
        # getattr: tests drive _beat on partially-constructed supervisors
        # (TrainingSupervisor.__new__) that predate the elastic attrs.
        leases = getattr(self, "host_leases", None)
        if step is not None and leases is not None:
            dead = leases.check()              # never raises (liveness)
            if dead:
                # actuation, ordered AFTER the train.host.dead verdict the
                # check just journaled: shrink the plan over the survivors
                # (full elastic path) or at least drain the dead hosts'
                # chunks. Failure here must not kill the surviving loop.
                try:
                    elastic = getattr(self, "elastic", None)
                    if elastic is not None:
                        elastic.shrink(dead)
                    elif self.chunk_planner is not None:
                        self.chunk_planner.remove_hosts(dead)
                except Exception as e:  # noqa: BLE001
                    logger.warning("elastic shrink failed (%s: %s)",
                                   type(e).__name__, e)

    def _mark(self, step: int, results: list, write: bool) -> None:
        t0 = time.perf_counter()
        payload = self._snapshot(step, results)
        self._last = (step, payload, list(results))
        if write:
            self.writer.submit(step, payload)
        # snapshot+submit is the checkpoint STALL the step thread pays
        # (the disk write itself rides the async writer); a durable mark
        # also resets the rewindable-wall window
        self.clock.note("checkpoint", time.perf_counter() - t0)
        self.clock.marked()
        self._beat(step)

    def _finalize(self, step: int, results: list, preempted: bool) -> None:
        t0 = time.perf_counter()
        payload = self._snapshot(step, results)
        payload[PREEMPTED_KEY] = bool(preempted)
        try:
            self.writer.write_sync(step, payload)
        except Exception as e:  # noqa: BLE001 - see preempt contract below
            if not preempted:
                raise   # a clean finish must not hide a lost final write
            # preemption: the clean-exit contract (Preempted raised, the
            # scheduler sees an orderly shutdown) outranks the final write
            # — a wedged flush (slow NFS, stuck disk) must not turn a
            # preemption into a crash. Best effort: try the direct write
            # anyway (its step dir is distinct from the in-flight one);
            # failing that, the periodic checkpoints still allow resume.
            self.metrics.inc(tnames.CHECKPOINT_FINALIZE_ERRORS)
            logger.warning("final preemption checkpoint write failed "
                           "(%s: %s); resuming will use the last periodic "
                           "checkpoint", type(e).__name__, e)
            try:
                self.manager.save(step, payload)
            except Exception:  # noqa: BLE001
                pass
        # the final synchronous write (and its queue drain) is checkpoint
        # stall too; publish so the run's last gauges include it
        self.clock.note("checkpoint", time.perf_counter() - t0)
        self.clock.publish()
        if preempted:
            self.metrics.inc(tnames.TRAIN_PREEMPTED)
            get_tracer().event(tnames.TRAIN_PREEMPTED_EVENT, step=step,
                               signum=self._preempt)
            self._beat(step)
        else:
            self._beat(None)   # clean finish: next start is fresh

    # -- signals --------------------------------------------------------------
    def _install_signals(self):
        if not self.handle_signals:
            return None

        def handler(signum, frame):
            self._preempt = signum
            self.metrics.inc(tnames.TRAIN_PREEMPT_SIGNALS)

        old = {}
        for sig in (_signal.SIGTERM, _signal.SIGINT):
            try:
                old[sig] = _signal.signal(sig, handler)
            except ValueError:   # not the main thread: poll-only preemption
                break
        return old

    def _restore_signals(self, old) -> None:
        if not old:
            return
        for sig, prev in old.items():
            try:
                _signal.signal(sig, prev)
            except ValueError:
                pass
