"""AccessAnomaly: collaborative-filtering anomaly detection over
(tenant, user, resource) access logs.

Role-equivalent to the reference's
mmlspark/cyber/anomaly/collaborative_filtering.py (988 LoC around pyspark
ALS) and complement_access.py. TPU-first redesign: per tenant, the
user x resource interaction matrix is DENSE on device and the ALS
factorization is two batched ridge solves per iteration (alternating least
squares = exactly the MXU's favorite shape) instead of Spark's blocked ALS.

Scoring matches the reference's semantics: likelihood = u . v for the
(user, resource) pair; scores are standardized per tenant on the training
history so 'normal' accesses sit near 0 and unlikely ones score HIGH
(AccessAnomalyModel.transform flips the standardized likelihood sign).
Unseen users/resources score 0 (no evidence), like the reference's
null-handling dot udf.
"""
from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Param, Table, Transformer
from ..core.params import in_range
from ..ops.levels import lookup_levels


class ComplementAccessTransformer(Transformer):
    """Sample (tenant, user, res) tuples ABSENT from the observed access set
    (reference: cyber/anomaly/complement_access.py): factor x |observed| rows
    per tenant, drawn uniformly from the complement."""
    tenant_col = Param("tenant_col", "tenant column", "tenant")
    indexed_user_col = Param("indexed_user_col", "user index column", "user_ix")
    indexed_res_col = Param("indexed_res_col", "resource index column", "res_ix")
    complementset_factor = Param("complementset_factor",
                                 "complement rows per observed row", 2,
                                 validator=in_range(1))
    seed = Param("seed", "sampling seed", 0)

    def _transform(self, t: Table) -> Table:
        rng = np.random.default_rng(self.seed)
        tenants = np.asarray(t[self.tenant_col])
        users = np.asarray(t[self.indexed_user_col], np.int64)
        res = np.asarray(t[self.indexed_res_col], np.int64)
        out_t, out_u, out_r = [], [], []
        for ten in np.unique(tenants):
            m = tenants == ten
            seen = set(zip(users[m].tolist(), res[m].tolist()))
            # ids are 1-based (IdIndexer reserves 0 for unseen) — never
            # fabricate complement tuples with the sentinel id
            u_lo = 1 if users[m].min() >= 1 else 0
            r_lo = 1 if res[m].min() >= 1 else 0
            n_users = int(users[m].max()) + 1
            n_res = int(res[m].max()) + 1
            want = self.complementset_factor * int(m.sum())
            cap = (n_users - u_lo) * (n_res - r_lo) - len(seen)
            want = min(want, max(cap, 0))
            got = 0
            while got < want:
                cu = rng.integers(u_lo, n_users, size=want * 2)
                cr = rng.integers(r_lo, n_res, size=want * 2)
                for u, r in zip(cu.tolist(), cr.tolist()):
                    if (u, r) not in seen:
                        seen.add((u, r))
                        out_t.append(ten)
                        out_u.append(u)
                        out_r.append(r)
                        got += 1
                        if got >= want:
                            break
        return Table({self.tenant_col: np.asarray(out_t),
                      self.indexed_user_col: np.asarray(out_u, np.int64),
                      self.indexed_res_col: np.asarray(out_r, np.int64)},
                     t.npartitions)


def _als(ratings: np.ndarray, weights: np.ndarray, rank: int, iters: int,
         reg: float, seed: int):
    """Weighted dense ALS: alternate batched ridge solves on device."""
    import jax
    import jax.numpy as jnp

    n_u, n_r = ratings.shape
    rng = np.random.default_rng(seed)
    u0 = jnp.asarray(rng.normal(scale=0.1, size=(n_u, rank)), jnp.float32)
    v0 = jnp.asarray(rng.normal(scale=0.1, size=(n_r, rank)), jnp.float32)
    r_j = jnp.asarray(ratings, jnp.float32)
    w_j = jnp.asarray(weights, jnp.float32)

    @jax.jit
    def run(u, v):
        def solve_side(fixed, r, w):
            # rows of `r`/`w`: for each entity, solve
            # (F^T W F + reg I) x = F^T W r  — vmapped ridge, one batch
            def one(r_row, w_row):
                fw = fixed * w_row[:, None]
                gram = fixed.T @ fw + reg * jnp.eye(rank, dtype=jnp.float32)
                rhs = fw.T @ r_row
                return jnp.linalg.solve(gram, rhs)
            return jax.vmap(one)(r, w)

        def step(carry, _):
            u, v = carry
            u = solve_side(v, r_j, w_j)
            v = solve_side(u, r_j.T, w_j.T)
            return (u, v), None

        (u, v), _ = jax.lax.scan(step, (u, v), None, length=iters)
        return u, v

    u, v = run(u0, v0)
    return np.asarray(u), np.asarray(v)


class AccessAnomaly(Estimator):
    """Fit per-tenant user/resource latent factors on access history
    (reference: collaborative_filtering.py AccessAnomaly)."""
    tenant_col = Param("tenant_col", "tenant column", "tenant")
    user_col = Param("user_col", "user column", "user")
    res_col = Param("res_col", "resource column", "res")
    likelihood_col = Param("likelihood_col",
                           "optional access-count/likelihood column", None)
    output_col = Param("output_col", "anomaly score column", "anomaly_score")
    rank = Param("rank", "latent dimension", 10, validator=in_range(1))
    max_iter = Param("max_iter", "ALS iterations", 25, validator=in_range(1))
    reg_param = Param("reg_param", "ridge regularization", 1.0)
    low_value = Param("low_value", "rating assigned to rare accesses", 5.0)
    high_value = Param("high_value", "rating for frequent accesses", 10.0)
    complementset_factor = Param("complementset_factor",
                                 "negative samples per observed row", 2)
    neg_score = Param("neg_score", "rating for complement rows", 1.0)
    seed = Param("seed", "random seed", 0)

    def _fit(self, t: Table) -> "AccessAnomalyModel":
        tenants = np.asarray(t[self.tenant_col])
        users = np.asarray(t[self.user_col])
        res = np.asarray(t[self.res_col])
        counts = (np.asarray(t[self.likelihood_col], np.float64)
                  if self.likelihood_col and self.likelihood_col in t
                  else np.ones(len(t)))

        models = {}
        for ten in np.unique(tenants):
            m = tenants == ten
            u_levels, u_ix = np.unique(users[m], return_inverse=True)
            r_levels, r_ix = np.unique(res[m], return_inverse=True)
            n_u, n_r = len(u_levels), len(r_levels)
            # observed ratings scaled into [low, high] by frequency
            mat = np.zeros((n_u, n_r), np.float64)
            np.add.at(mat, (u_ix, r_ix), counts[m])
            obs = mat > 0
            if not obs.any():
                # no positive evidence for this tenant: nothing to factorize;
                # transform scores its rows 0 ("no evidence"), same as unseen
                continue
            if mat[obs].max() > mat[obs].min():
                lo, hi = mat[obs].min(), mat[obs].max()
                scaled = (self.low_value
                          + (mat - lo) * (self.high_value - self.low_value)
                          / (hi - lo))
            else:
                scaled = np.full_like(mat, self.high_value)
            ratings = np.where(obs, scaled, self.neg_score)
            # weights: observed 1; unobserved cells get the complement-set
            # weight factor/|cells| so negatives softly pull scores down
            # (the reference materializes factor x N sampled complement rows;
            # a dense weighted fill is the same pull, fully vectorized)
            n_neg = (~obs).sum()
            w_neg = min(self.complementset_factor * obs.sum()
                        / max(n_neg, 1), 1.0)
            weights = np.where(obs, 1.0, w_neg)
            u_vec, v_vec = _als(ratings, weights, self.rank, self.max_iter,
                                self.reg_param, self.seed)
            # standardization stats of the observed likelihoods
            scores = (u_vec[u_ix] * v_vec[r_ix]).sum(axis=1)
            mean, std = float(scores.mean()), float(scores.std() or 1.0)
            models[str(ten)] = (u_levels, u_vec, r_levels, v_vec, mean, std)

        m = AccessAnomalyModel(**{p: getattr(self, p) for p in
                                  ("tenant_col", "user_col", "res_col",
                                   "output_col")})
        m._models = models
        return m


class AccessAnomalyModel(Model):
    tenant_col = Param("tenant_col", "tenant column", "tenant")
    user_col = Param("user_col", "user column", "user")
    res_col = Param("res_col", "resource column", "res")
    output_col = Param("output_col", "anomaly score column", "anomaly_score")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._models = {}

    def _get_state(self):
        out = {"tenants": np.asarray(list(self._models), dtype=object)}
        for i, (ten, (ul, uv, rl, rv, mean, std)) in enumerate(
                self._models.items()):
            out[f"ul_{i}"] = np.asarray(ul)
            out[f"uv_{i}"] = np.asarray(uv, np.float32)
            out[f"rl_{i}"] = np.asarray(rl)
            out[f"rv_{i}"] = np.asarray(rv, np.float32)
            out[f"ms_{i}"] = np.asarray([mean, std], np.float64)
        return out

    def _set_state(self, s):
        self._models = {}
        for i, ten in enumerate(np.asarray(s["tenants"])):
            ms = np.asarray(s[f"ms_{i}"])
            self._models[str(ten)] = (
                np.asarray(s[f"ul_{i}"]), np.asarray(s[f"uv_{i}"]),
                np.asarray(s[f"rl_{i}"]), np.asarray(s[f"rv_{i}"]),
                float(ms[0]), float(ms[1]))

    def _lookup(self, levels, vecs, vals):
        idx, found = lookup_levels(levels, vals)
        return vecs[idx], found

    def _transform(self, t: Table) -> Table:
        tenants = np.asarray(t[self.tenant_col])
        users = np.asarray(t[self.user_col])
        res = np.asarray(t[self.res_col])
        out = np.zeros(len(t))
        for ten in np.unique(tenants):
            key = str(ten)
            if key not in self._models:
                continue
            ul, uv, rl, rv, mean, std = self._models[key]
            m = tenants == ten
            u_vecs, u_ok = self._lookup(ul, uv, users[m])
            r_vecs, r_ok = self._lookup(rl, rv, res[m])
            lik = (u_vecs * r_vecs).sum(axis=1)
            z = (lik - mean) / (std or 1.0)
            score = np.where(u_ok & r_ok, -z, 0.0)  # low likelihood => high score
            out[m] = score
        return t.with_column(self.output_col, out)
