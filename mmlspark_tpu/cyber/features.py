"""Per-tenant feature utilities (reference: mmlspark/cyber/feature/indexers.py
and scalers.py — the reference's are pyspark wrappers around per-partition
groupBy; here they are vectorized per-tenant numpy passes over Table columns).
"""
from __future__ import annotations

import numpy as np

from ..core import Estimator, Model, Param, Table
from ..core.params import HasInputCol, HasOutputCol
from ..ops.levels import lookup_levels


class _HasTenant:
    tenant_col = Param("tenant_col", "tenant partition column", "tenant")


def _tenant_groups(t: Table, tenant_col: str):
    tenants = np.asarray(t[tenant_col])
    uniq, inv = np.unique(tenants, return_inverse=True)
    return uniq, inv


class IdIndexer(Estimator, _HasTenant, HasInputCol, HasOutputCol):
    """Per-tenant value -> dense 1-based index (reference:
    feature/indexers.py IdIndexer: ids are partitioned by tenant)."""

    def _fit(self, t: Table) -> "IdIndexerModel":
        uniq_t, inv = _tenant_groups(t, self.tenant_col)
        col = np.asarray(t[self.input_col])
        mapping = {}
        for k, ten in enumerate(uniq_t):
            vals = np.unique(col[inv == k])
            mapping[str(ten)] = {v: i + 1 for i, v in enumerate(vals)}
        m = IdIndexerModel(**{p: getattr(self, p) for p in
                              ("tenant_col", "input_col", "output_col")})
        m._mapping = mapping
        return m


class IdIndexerModel(Model, _HasTenant, HasInputCol, HasOutputCol):
    def __init__(self, **kw):
        super().__init__(**kw)
        self._mapping = {}

    def _get_state(self):
        # mapping as parallel arrays per tenant
        out = {"tenants": np.asarray(list(self._mapping), dtype=object)}
        for i, (ten, mp) in enumerate(self._mapping.items()):
            out[f"keys_{i}"] = np.asarray(list(mp), dtype=object)
        return out

    def _set_state(self, s):
        self._mapping = {}
        for i, ten in enumerate(np.asarray(s["tenants"])):
            keys = np.asarray(s[f"keys_{i}"])
            self._mapping[str(ten)] = {k: j + 1 for j, k in enumerate(keys)}

    def vocab_size(self, tenant) -> int:
        return len(self._mapping.get(str(tenant), {}))

    def _transform(self, t: Table) -> Table:
        tenants = np.asarray(t[self.tenant_col])
        col = np.asarray(t[self.input_col])
        out = np.zeros(len(t), np.int64)  # unseen -> 0 (reference: undefined)
        for ten in np.unique(tenants):
            mp = self._mapping.get(str(ten))
            if not mp:
                continue
            m = tenants == ten
            keys = np.asarray(sorted(mp))
            idx, found = lookup_levels(keys, col[m])
            # mapping values are 1-based positions in insertion order; keys
            # were stored sorted, so position-in-sorted IS the id
            out[m] = np.where(found, idx + 1, 0)
        return t.with_column(self.output_col, out)


class StandardScalarScaler(Estimator, _HasTenant, HasInputCol, HasOutputCol):
    """Per-tenant standardization to target mean/std (reference:
    feature/scalers.py StandardScalarScaler)."""
    coefficient_factor = Param("coefficient_factor",
                               "multiplier on the standardized value", 1.0)

    def _fit(self, t: Table) -> "StandardScalarScalerModel":
        uniq_t, inv = _tenant_groups(t, self.tenant_col)
        col = np.asarray(t[self.input_col], np.float64)
        stats = {}
        for k, ten in enumerate(uniq_t):
            v = col[inv == k]
            stats[str(ten)] = (float(v.mean()), float(v.std() or 1.0))
        m = StandardScalarScalerModel(
            **{p: getattr(self, p) for p in
               ("tenant_col", "input_col", "output_col", "coefficient_factor")})
        m._stats = stats
        return m


class StandardScalarScalerModel(Model, _HasTenant, HasInputCol, HasOutputCol):
    coefficient_factor = Param("coefficient_factor", "multiplier", 1.0)

    def __init__(self, **kw):
        super().__init__(**kw)
        self._stats = {}

    def _get_state(self):
        return {"tenants": np.asarray(list(self._stats), dtype=object),
                "mean_std": np.asarray([list(v) for v in self._stats.values()],
                                       np.float64).reshape(-1, 2)}

    def _set_state(self, s):
        ms = np.asarray(s["mean_std"]).reshape(-1, 2)
        self._stats = {str(t): (float(m), float(sd))
                       for t, (m, sd) in zip(np.asarray(s["tenants"]), ms)}

    def _transform(self, t: Table) -> Table:
        tenants = np.asarray(t[self.tenant_col])
        col = np.asarray(t[self.input_col], np.float64)
        out = np.empty(len(t))
        for ten in np.unique(tenants):
            mean, std = self._stats.get(str(ten), (0.0, 1.0))
            m = tenants == ten
            out[m] = self.coefficient_factor * (col[m] - mean) / (std or 1.0)
        return t.with_column(self.output_col, out)


class LinearScalarScaler(Estimator, _HasTenant, HasInputCol, HasOutputCol):
    """Per-tenant linear map of [min, max] -> [min_required, max_required]
    (reference: feature/scalers.py LinearScalarScaler)."""
    min_required_value = Param("min_required_value", "output min", 0.0)
    max_required_value = Param("max_required_value", "output max", 1.0)

    def _fit(self, t: Table) -> "LinearScalarScalerModel":
        uniq_t, inv = _tenant_groups(t, self.tenant_col)
        col = np.asarray(t[self.input_col], np.float64)
        stats = {}
        for k, ten in enumerate(uniq_t):
            v = col[inv == k]
            lo, hi = float(v.min()), float(v.max())
            if hi == lo:
                a, b = 0.0, self.max_required_value
            else:
                a = (self.max_required_value - self.min_required_value) / (hi - lo)
                b = self.min_required_value - a * lo
            stats[str(ten)] = (a, b)
        m = LinearScalarScalerModel(
            **{p: getattr(self, p) for p in
               ("tenant_col", "input_col", "output_col")})
        m._stats = stats
        return m


class LinearScalarScalerModel(Model, _HasTenant, HasInputCol, HasOutputCol):
    def __init__(self, **kw):
        super().__init__(**kw)
        self._stats = {}

    def _get_state(self):
        return {"tenants": np.asarray(list(self._stats), dtype=object),
                "ab": np.asarray([list(v) for v in self._stats.values()],
                                 np.float64).reshape(-1, 2)}

    def _set_state(self, s):
        ab = np.asarray(s["ab"]).reshape(-1, 2)
        self._stats = {str(t): (float(a), float(b))
                       for t, (a, b) in zip(np.asarray(s["tenants"]), ab)}

    def _transform(self, t: Table) -> Table:
        tenants = np.asarray(t[self.tenant_col])
        col = np.asarray(t[self.input_col], np.float64)
        out = np.empty(len(t))
        for ten in np.unique(tenants):
            a, b = self._stats.get(str(ten), (1.0, 0.0))
            m = tenants == ten
            out[m] = a * col[m] + b
        return t.with_column(self.output_col, out)
