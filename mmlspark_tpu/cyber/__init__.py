"""CyberML (reference: mmlspark/cyber — SURVEY.md §2.8)."""
from .dataset import DataFactory
from .access_anomaly import (AccessAnomaly, AccessAnomalyModel,
                             ComplementAccessTransformer)
from .features import (IdIndexer, IdIndexerModel, LinearScalarScaler,
                       LinearScalarScalerModel, StandardScalarScaler,
                       StandardScalarScalerModel)

__all__ = ["AccessAnomaly", "AccessAnomalyModel", "DataFactory",
           "ComplementAccessTransformer", "IdIndexer", "IdIndexerModel",
           "LinearScalarScaler", "LinearScalarScalerModel",
           "StandardScalarScaler", "StandardScalarScalerModel"]
