"""Synthetic access-log factory for CyberML experiments.

Role-equivalent to the reference's cyber DataFactory
(python/mmlspark/cyber/dataset.py): three departments whose users access
their own department's resources (training distribution), plus generators
for unseen SAME-department pairs (normal test traffic) and CROSS-department
pairs (anomalous test traffic). AccessAnomaly should score the latter
clearly higher."""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core import Table


class DataFactory:
    """Clustered user->resource access generator.

    Departments are fully separate components; `single_component=True` adds
    one shared "free-for-all" resource every user touches so the access
    graph is connected (same trick as the reference)."""

    def __init__(self, num_hr_users: int = 7, num_hr_resources: int = 30,
                 num_fin_users: int = 5, num_fin_resources: int = 25,
                 num_eng_users: int = 10, num_eng_resources: int = 50,
                 single_component: bool = True, seed: int = 42):
        self.departments = {
            "hr": ([f"hr_user_{i}" for i in range(num_hr_users)],
                   [f"hr_res_{i}" for i in range(num_hr_resources)]),
            "fin": ([f"fin_user_{i}" for i in range(num_fin_users)],
                    [f"fin_res_{i}" for i in range(num_fin_resources)]),
            "eng": ([f"eng_user_{i}" for i in range(num_eng_users)],
                    [f"eng_res_{i}" for i in range(num_eng_resources)]),
        }
        self.join_resources = ["ffa"] if single_component else []
        self._rng = np.random.default_rng(seed)

    def _table(self, edges) -> Table:
        users = np.asarray([e[0] for e in edges], dtype=object)
        res = np.asarray([e[1] for e in edges], dtype=object)
        lik = np.asarray([e[2] for e in edges], dtype=np.float64)
        tenants = np.zeros(len(edges), dtype=np.int64)
        return Table({"tenant": tenants, "user": users, "res": res,
                      "likelihood": lik})

    def _edges_between(self, users: Sequence[str], resources: Sequence[str],
                       ratio: float, full_coverage: bool,
                       exclude: Optional[set] = None):
        """Random bipartite edges: each (user, resource) pair appears with
        probability `ratio`; `full_coverage` guarantees every user and every
        resource touches at least one edge; `exclude` skips known pairs."""
        edges, covered_u, covered_r = [], set(), set()
        exclude = exclude or set()
        for u in users:
            for r in resources:
                if (u, r) in exclude:
                    continue
                if self._rng.random() < ratio:
                    edges.append((u, r, float(self._rng.integers(500, 1001))))
                    covered_u.add(u)
                    covered_r.add(r)
        if full_coverage:
            for u in users:
                if u not in covered_u and resources:
                    r = resources[int(self._rng.integers(len(resources)))]
                    edges.append((u, r, float(self._rng.integers(500, 1001))))
            for r in resources:
                if r not in covered_r and users:
                    u = users[int(self._rng.integers(len(users)))]
                    edges.append((u, r, float(self._rng.integers(500, 1001))))
        return edges

    def _join_edges(self):
        out = []
        for users, _ in self.departments.values():
            out += self._edges_between(users, self.join_resources, 1.0, True)
        return out

    def create_clustered_training_data(self, ratio: float = 0.25) -> Table:
        """Intra-department access at the given density (+ join edges)."""
        edges = self._join_edges()
        for users, res in self.departments.values():
            edges += self._edges_between(users, res, ratio, True)
        return self._table(edges)

    def create_clustered_intra_test_data(self,
                                         train: Optional[Table] = None
                                         ) -> Table:
        """Sparse SAME-department pairs, excluding pairs seen in `train` —
        plausible unseen traffic, should score low."""
        seen = set()
        if train is not None:
            seen = set(zip(train["user"].tolist(), train["res"].tolist()))
        edges = self._join_edges()
        for dept, (users, res) in self.departments.items():
            ratio = {"hr": 0.025, "fin": 0.05, "eng": 0.035}[dept]
            edges += self._edges_between(users, res, ratio, False, seen)
        return self._table(edges)

    def create_clustered_inter_test_data(self) -> Table:
        """Sparse CROSS-department pairs — anomalous traffic, should score
        high."""
        edges = self._join_edges()
        names = list(self.departments)
        for a in names:
            for b in names:
                if a == b:
                    continue
                users = self.departments[a][0]
                res = self.departments[b][1]
                ratio = {"hr": 0.025, "fin": 0.05, "eng": 0.035}[a]
                edges += self._edges_between(users, res, ratio, False)
        return self._table(edges)
