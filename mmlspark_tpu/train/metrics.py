"""Metric computation core (reference: core/metrics/MetricConstants.scala,
train/ComputeModelStatistics.scala:58-470). Vectorized numpy/JAX over whole
columns — the reference's RDD MulticlassMetrics/BinaryClassificationMetrics
become closed-form array ops.
"""
from __future__ import annotations

import numpy as np

# reference: MetricConstants.scala names
CLASSIFICATION_METRICS = ["accuracy", "precision", "recall", "AUC"]
REGRESSION_METRICS = ["mse", "rmse", "r2", "mae"]


def confusion_matrix(y_true, y_pred, n_classes=None):
    y_true = np.asarray(y_true).astype(int)
    y_pred = np.asarray(y_pred).astype(int)
    k = n_classes or int(max(y_true.max(), y_pred.max())) + 1
    cm = np.zeros((k, k), dtype=np.int64)
    np.add.at(cm, (y_true, y_pred), 1)
    return cm


def auc(y_true, scores):
    """Rank-statistic AUC (Mann-Whitney), ties averaged."""
    y_true = np.asarray(y_true)
    scores = np.asarray(scores, dtype=np.float64)
    # average ranks for ties
    uniq, inv, counts = np.unique(scores, return_inverse=True, return_counts=True)
    cum = np.cumsum(counts)
    avg_rank = cum - (counts - 1) / 2.0
    ranks = avg_rank[inv]
    npos = float(y_true.sum())
    nneg = float(len(y_true) - npos)
    if npos == 0 or nneg == 0:
        return 0.5
    return float((ranks[y_true == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg))


def pr_auc(y_true, scores):
    """Area under precision-recall curve (AUPR)."""
    y_true = np.asarray(y_true)
    scores = np.asarray(scores, dtype=np.float64)
    order = np.argsort(-scores, kind="stable")
    y = y_true[order]
    s = scores[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    npos = y.sum()
    if npos == 0:
        return 0.0
    # evaluate only at distinct-threshold boundaries (tie groups collapse),
    # matching sklearn's average_precision_score convention
    distinct = np.r_[s[1:] != s[:-1], True]
    tp, fp = tp[distinct], fp[distinct]
    precision = tp / np.maximum(tp + fp, 1)
    recall = tp / npos
    d_recall = np.diff(np.concatenate([[0.0], recall]))
    return float((precision * d_recall).sum())


def binary_metrics(y_true, scores, y_pred=None, threshold=0.5):
    y_true = np.asarray(y_true)
    scores = np.asarray(scores)
    if y_pred is None:
        y_pred = (scores >= threshold).astype(float)
    cm = confusion_matrix(y_true, y_pred, 2)
    tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
    out = {
        "accuracy": (tp + tn) / max(cm.sum(), 1),
        "precision": tp / max(tp + fp, 1),
        "recall": tp / max(tp + fn, 1),
        "AUC": auc(y_true, scores),
        "AUPR": pr_auc(y_true, scores),
    }
    out["f1"] = (2 * out["precision"] * out["recall"]
                 / max(out["precision"] + out["recall"], 1e-12))
    return out, cm


def multiclass_metrics(y_true, y_pred, n_classes=None):
    """Macro/micro averaged metrics from the paper formulas the reference
    cites (ComputeModelStatistics.scala:330-436)."""
    cm = confusion_matrix(y_true, y_pred, n_classes)
    k = cm.shape[0]
    tp = np.diag(cm).astype(np.float64)
    fp = cm.sum(axis=0) - tp
    fn = cm.sum(axis=1) - tp
    total = cm.sum()
    per_class_precision = tp / np.maximum(tp + fp, 1)
    per_class_recall = tp / np.maximum(tp + fn, 1)
    micro_p = tp.sum() / max((tp + fp).sum(), 1)
    micro_r = tp.sum() / max((tp + fn).sum(), 1)
    out = {
        "accuracy": tp.sum() / max(total, 1),
        "precision": micro_p,        # micro (reference default)
        "recall": micro_r,
        "macro_precision": per_class_precision.mean(),
        "macro_recall": per_class_recall.mean(),
        "AUC": float("nan"),
    }
    return out, cm


def regression_metrics(y_true, y_pred):
    y_true = np.asarray(y_true, dtype=np.float64)
    y_pred = np.asarray(y_pred, dtype=np.float64)
    resid = y_true - y_pred
    mse = float((resid ** 2).mean())
    var = float(((y_true - y_true.mean()) ** 2).mean())
    return {
        "mse": mse,
        "rmse": float(np.sqrt(mse)),
        "r2": 1.0 - mse / max(var, 1e-300),
        "mae": float(np.abs(resid).mean()),
    }


def per_instance_classification(y_true, probabilities):
    """Per-row log-loss (reference: ComputePerInstanceStatistics)."""
    probabilities = np.asarray(probabilities)
    y = np.asarray(y_true).astype(int)
    p = np.clip(probabilities[np.arange(len(y)), y], 1e-15, 1.0)
    return {"log_loss": -np.log(p)}


def per_instance_regression(y_true, y_pred):
    resid = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return {"L1_loss": np.abs(resid), "L2_loss": resid ** 2}


def ndcg_at_k(labels_by_group, scores_by_group, k=10):
    """Mean NDCG@k over query groups (for the ranking evaluator)."""
    vals = []
    for lab, sc in zip(labels_by_group, scores_by_group):
        lab = np.asarray(lab, dtype=np.float64)
        order = np.argsort(-np.asarray(sc))[:k]
        gains = (2 ** lab[order] - 1) / np.log2(np.arange(2, len(order) + 2))
        ideal_order = np.argsort(-lab)[:k]
        ideal = (2 ** lab[ideal_order] - 1) / np.log2(np.arange(2, len(ideal_order) + 2))
        vals.append(gains.sum() / max(ideal.sum(), 1e-12))
    return float(np.mean(vals)) if vals else 0.0
