"""Metric computation core (reference: core/metrics/MetricConstants.scala,
train/ComputeModelStatistics.scala:58-470). Vectorized numpy/JAX over whole
columns — the reference's RDD MulticlassMetrics/BinaryClassificationMetrics
become closed-form array ops.

The sufficient statistics live in MERGEABLE state objects
(`ConfusionState` for classification, `RegressionState` for regression):
counts and sums that add exactly across chunks and across workers —
counts sum, never averaged, the same contract as
`reliability.metrics.Histogram` bucket merges. The batch functions below
(`multiclass_metrics`, `binary_metrics`, `regression_metrics`) are thin
wrappers that build a state from whole arrays and finalize it, and the
streaming evaluator (`telemetry.quality.StreamingEvaluator`) folds the
SAME states row by row — one finalize kernel, so batch
`ComputeModelStatistics` and online evaluation cannot drift
(tests/test_quality.py pins streaming-merge-over-chunks ==
batch-over-concatenation). Rank statistics (AUC/AUPR/NDCG) need the full
score ordering and stay batch-only.
"""
from __future__ import annotations

import numpy as np

# reference: MetricConstants.scala names
CLASSIFICATION_METRICS = ["accuracy", "precision", "recall", "AUC"]
REGRESSION_METRICS = ["mse", "rmse", "r2", "mae"]


class ConfusionState:
    """Mergeable confusion-matrix state: a (k, k) int64 count matrix that
    grows as new class ids arrive. `update` folds arrays, `merge` sums
    two states exactly (padding to the larger k), and `metrics()` is THE
    classification finalize kernel — the macro/micro formulas the
    reference cites (ComputeModelStatistics.scala:330-436), shared
    verbatim by the batch transformers and the streaming evaluator."""

    __slots__ = ("cm",)

    def __init__(self, n_classes: int = 2):
        k = max(int(n_classes), 1)
        self.cm = np.zeros((k, k), dtype=np.int64)

    def _ensure(self, k: int) -> None:
        if k > self.cm.shape[0]:
            grown = np.zeros((k, k), dtype=np.int64)
            grown[:self.cm.shape[0], :self.cm.shape[1]] = self.cm
            self.cm = grown

    def update(self, y_true, y_pred) -> "ConfusionState":
        y_true = np.asarray(y_true).astype(int)
        y_pred = np.asarray(y_pred).astype(int)
        if y_true.size:
            self._ensure(int(max(y_true.max(), y_pred.max())) + 1)
            np.add.at(self.cm, (y_true, y_pred), 1)
        return self

    @classmethod
    def from_arrays(cls, y_true, y_pred, n_classes=None) -> "ConfusionState":
        if n_classes:
            # an EXPLICIT class count is a contract, not a floor: a label
            # outside [0, n_classes) raises (numpy fancy-index bounds)
            # exactly like the pre-state confusion_matrix kernel did —
            # silently growing the matrix would fold stray labels into
            # metrics whose reader asked for k classes
            st = cls(n_classes)
            y_true = np.asarray(y_true).astype(int)
            y_pred = np.asarray(y_pred).astype(int)
            np.add.at(st.cm, (y_true, y_pred), 1)
            return st
        return cls(1).update(y_true, y_pred)

    def merge(self, other: "ConfusionState") -> "ConfusionState":
        """Exact merge: integer counts sum (never averaged)."""
        self._ensure(other.cm.shape[0])
        self.cm[:other.cm.shape[0], :other.cm.shape[1]] += other.cm
        return self

    # -- raw state (JSON round-trip / cross-worker merge) ---------------------
    def state(self) -> dict:
        return {"cm": self.cm.tolist()}

    @classmethod
    def from_state(cls, state: dict) -> "ConfusionState":
        st = cls(1)
        st.cm = np.asarray(state["cm"], dtype=np.int64)
        if st.cm.ndim != 2 or st.cm.shape[0] != st.cm.shape[1]:
            raise ValueError("confusion state must be a square count matrix")
        return st

    # -- finalize kernels -----------------------------------------------------
    @property
    def count(self) -> int:
        return int(self.cm.sum())

    def metrics(self) -> dict:
        """Macro/micro averaged classification metrics from the counts."""
        cm = self.cm
        tp = np.diag(cm).astype(np.float64)
        fp = cm.sum(axis=0) - tp
        fn = cm.sum(axis=1) - tp
        total = cm.sum()
        per_class_precision = tp / np.maximum(tp + fp, 1)
        per_class_recall = tp / np.maximum(tp + fn, 1)
        micro_p = tp.sum() / max((tp + fp).sum(), 1)
        micro_r = tp.sum() / max((tp + fn).sum(), 1)
        return {
            "accuracy": tp.sum() / max(total, 1),
            "precision": micro_p,        # micro (reference default)
            "recall": micro_r,
            "macro_precision": per_class_precision.mean(),
            "macro_recall": per_class_recall.mean(),
            "AUC": float("nan"),
        }

    def binary(self) -> dict:
        """The 2x2 rates (accuracy/precision/recall/f1) — the
        threshold-side half of `binary_metrics` (AUC/AUPR need the full
        score ordering and stay batch-only)."""
        self._ensure(2)
        cm = self.cm
        tn, fp, fn, tp = cm[0, 0], cm[0, 1], cm[1, 0], cm[1, 1]
        out = {
            "accuracy": (tp + tn) / max(cm.sum(), 1),
            "precision": tp / max(tp + fp, 1),
            "recall": tp / max(tp + fn, 1),
        }
        out["f1"] = (2 * out["precision"] * out["recall"]
                     / max(out["precision"] + out["recall"], 1e-12))
        return out


class RegressionState:
    """Mergeable regression sufficient statistics. The label side is
    held as Welford moments (n, mean, M2) and merged with Chan's
    parallel combine — NOT as raw sum(y)/sum(y^2), whose cancellation
    makes the variance (and so r2) garbage for labels with a large mean
    offset (y ~ 1e8 ± 1 has both terms at 1e16 with ulp ~ 2). Residual
    sums are safe raw: mse/mae are the quantities themselves, no
    cancellation. `metrics()` is THE regression finalize kernel
    (mse/rmse/r2/mae), shared by batch and streaming."""

    __slots__ = ("n", "mean_y", "m2_y", "sum_resid2", "sum_abs")

    def __init__(self):
        self.n = 0
        self.mean_y = 0.0
        self.m2_y = 0.0
        self.sum_resid2 = 0.0
        self.sum_abs = 0.0

    def _merge_moments(self, n: int, mean: float, m2: float) -> None:
        from ..utils.stats import merge_moments
        self.n, self.mean_y, self.m2_y = merge_moments(
            self.n, self.mean_y, self.m2_y, n, mean, m2)

    def update(self, y_true, y_pred) -> "RegressionState":
        y = np.asarray(y_true, dtype=np.float64)
        p = np.asarray(y_pred, dtype=np.float64)
        resid = y - p
        if y.size:
            mean = float(y.mean())
            self._merge_moments(int(y.size), mean,
                                float(((y - mean) ** 2).sum()))
        self.sum_resid2 += float((resid ** 2).sum())
        self.sum_abs += float(np.abs(resid).sum())
        return self

    @classmethod
    def from_arrays(cls, y_true, y_pred) -> "RegressionState":
        return cls().update(y_true, y_pred)

    def merge(self, other: "RegressionState") -> "RegressionState":
        self._merge_moments(other.n, other.mean_y, other.m2_y)
        self.sum_resid2 += other.sum_resid2
        self.sum_abs += other.sum_abs
        return self

    def state(self) -> dict:
        return {"n": self.n, "mean_y": self.mean_y, "m2_y": self.m2_y,
                "sum_resid2": self.sum_resid2, "sum_abs": self.sum_abs}

    @classmethod
    def from_state(cls, state: dict) -> "RegressionState":
        st = cls()
        st.n = int(state["n"])
        st.mean_y = float(state["mean_y"])
        st.m2_y = float(state["m2_y"])
        st.sum_resid2 = float(state["sum_resid2"])
        st.sum_abs = float(state["sum_abs"])
        return st

    @property
    def count(self) -> int:
        return self.n

    def metrics(self) -> dict:
        n = max(self.n, 1)
        mse = self.sum_resid2 / n
        var = max(self.m2_y / n, 0.0)
        return {
            "mse": mse,
            "rmse": float(np.sqrt(mse)),
            "r2": 1.0 - mse / max(var, 1e-300),
            "mae": self.sum_abs / n,
        }


def confusion_matrix(y_true, y_pred, n_classes=None):
    return ConfusionState.from_arrays(y_true, y_pred, n_classes).cm


def auc(y_true, scores):
    """Rank-statistic AUC (Mann-Whitney), ties averaged."""
    y_true = np.asarray(y_true)
    scores = np.asarray(scores, dtype=np.float64)
    # average ranks for ties
    uniq, inv, counts = np.unique(scores, return_inverse=True, return_counts=True)
    cum = np.cumsum(counts)
    avg_rank = cum - (counts - 1) / 2.0
    ranks = avg_rank[inv]
    npos = float(y_true.sum())
    nneg = float(len(y_true) - npos)
    if npos == 0 or nneg == 0:
        return 0.5
    return float((ranks[y_true == 1].sum() - npos * (npos + 1) / 2) / (npos * nneg))


def pr_auc(y_true, scores):
    """Area under precision-recall curve (AUPR)."""
    y_true = np.asarray(y_true)
    scores = np.asarray(scores, dtype=np.float64)
    order = np.argsort(-scores, kind="stable")
    y = y_true[order]
    s = scores[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    npos = y.sum()
    if npos == 0:
        return 0.0
    # evaluate only at distinct-threshold boundaries (tie groups collapse),
    # matching sklearn's average_precision_score convention
    distinct = np.r_[s[1:] != s[:-1], True]
    tp, fp = tp[distinct], fp[distinct]
    precision = tp / np.maximum(tp + fp, 1)
    recall = tp / npos
    d_recall = np.diff(np.concatenate([[0.0], recall]))
    return float((precision * d_recall).sum())


def binary_metrics(y_true, scores, y_pred=None, threshold=0.5):
    y_true = np.asarray(y_true)
    scores = np.asarray(scores)
    if y_pred is None:
        y_pred = (scores >= threshold).astype(float)
    st = ConfusionState.from_arrays(y_true, y_pred, 2)
    out = st.binary()
    # rank statistics need the full score ordering — batch-only, layered
    # on top of the mergeable threshold-side state
    out["AUC"] = auc(y_true, scores)
    out["AUPR"] = pr_auc(y_true, scores)
    return out, st.cm


def multiclass_metrics(y_true, y_pred, n_classes=None):
    """Macro/micro averaged metrics from the paper formulas the reference
    cites (ComputeModelStatistics.scala:330-436) — built from the
    mergeable `ConfusionState` so the batch and streaming paths share one
    finalize kernel."""
    st = ConfusionState.from_arrays(y_true, y_pred, n_classes)
    return st.metrics(), st.cm


def regression_metrics(y_true, y_pred):
    return RegressionState.from_arrays(y_true, y_pred).metrics()


def per_instance_classification(y_true, probabilities):
    """Per-row log-loss (reference: ComputePerInstanceStatistics)."""
    probabilities = np.asarray(probabilities)
    y = np.asarray(y_true).astype(int)
    p = np.clip(probabilities[np.arange(len(y)), y], 1e-15, 1.0)
    return {"log_loss": -np.log(p)}


def per_instance_regression(y_true, y_pred):
    resid = np.asarray(y_true, np.float64) - np.asarray(y_pred, np.float64)
    return {"L1_loss": np.abs(resid), "L2_loss": resid ** 2}


def ndcg_at_k(labels_by_group, scores_by_group, k=10):
    """Mean NDCG@k over query groups (for the ranking evaluator)."""
    vals = []
    for lab, sc in zip(labels_by_group, scores_by_group):
        lab = np.asarray(lab, dtype=np.float64)
        order = np.argsort(-np.asarray(sc))[:k]
        gains = (2 ** lab[order] - 1) / np.log2(np.arange(2, len(order) + 2))
        ideal_order = np.argsort(-lab)[:k]
        ideal = (2 ** lab[ideal_order] - 1) / np.log2(np.arange(2, len(ideal_order) + 2))
        vals.append(gains.sum() / max(ideal.sum(), 1e-12))
    return float(np.mean(vals)) if vals else 0.0
