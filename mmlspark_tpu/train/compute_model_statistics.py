"""ComputeModelStatistics / ComputePerInstanceStatistics transformers
(reference: train/ComputeModelStatistics.scala:58-470, ComputePerInstanceStatistics).

Consume a scored Table (label + scores/probabilities/prediction columns) and
emit a one-row metrics Table (plus confusion matrix accessor) or per-row
statistics columns.

The metric math lives in `train.metrics`' mergeable state cores
(`ConfusionState`/`RegressionState`); the streaming evaluator on the
serving stream (`telemetry.quality.StreamingEvaluator`) folds the SAME
states, so this batch transformer and online evaluation share one
finalize kernel by construction (parity pinned in tests/test_quality.py).
"""
from __future__ import annotations

import logging

import numpy as np

from ..core import (Transformer, Param, Table, HasLabelCol, HasScoresCol,
                    HasScoredLabelsCol, Evaluator, one_of)
from . import metrics as M

_logger = logging.getLogger("mmlspark_tpu.metrics")


class ComputeModelStatistics(Transformer, HasLabelCol, HasScoredLabelsCol,
                             HasScoresCol):
    evaluation_metric = Param(
        "evaluation_metric", "classification|regression|auto", "auto",
        validator=one_of("auto", "classification", "regression"))
    scores_col = Param("scores_col", "probability/score column", None)
    scored_labels_col = Param("scored_labels_col", "predicted label column",
                              "prediction")

    def _resolve_kind(self, t: Table) -> str:
        kind = self.evaluation_metric
        if kind != "auto":
            return kind
        y = np.asarray(t[self.label_col])
        uniq = np.unique(y[~np.isnan(y.astype(np.float64))] if
                         np.issubdtype(y.dtype, np.floating) else y)
        is_int_like = np.issubdtype(y.dtype, np.integer) or (
            np.issubdtype(y.dtype, np.floating)
            and np.allclose(uniq, np.round(uniq)))
        return "classification" if (is_int_like and uniq.size <= 100) else "regression"

    def _transform(self, t: Table) -> Table:
        kind = self._resolve_kind(t)
        y = np.asarray(t[self.label_col], dtype=np.float64)
        pred_col = self.scored_labels_col
        if kind == "classification":
            pred = np.asarray(t[pred_col], dtype=np.float64)
            n_classes = int(max(y.max(), pred.max())) + 1
            scores = None
            scol = self.scores_col
            if scol is None:
                for cand in ("probabilities", "scores", "raw_prediction"):
                    if cand in t:
                        scol = cand
                        break
            if scol and scol in t:
                s = np.asarray(t[scol])
                scores = s[:, 1] if s.ndim == 2 and s.shape[1] == 2 else s
            if n_classes <= 2 and scores is not None and scores.ndim == 1:
                vals, cm = M.binary_metrics(y, scores, y_pred=pred)
            else:
                vals, cm = M.multiclass_metrics(y, pred, n_classes)
            self._confusion_matrix = cm
        else:
            pred = np.asarray(t[pred_col], dtype=np.float64)
            vals = M.regression_metrics(y, pred)
            self._confusion_matrix = None
        # MetricsLogger analog (ComputeModelStatistics.scala:473)
        _logger.info("model statistics: %s", vals)
        return Table({k: np.asarray([v]) for k, v in vals.items()})

    @property
    def confusion_matrix(self):
        return self._confusion_matrix


class ComputePerInstanceStatistics(Transformer, HasLabelCol, HasScoredLabelsCol):
    evaluation_metric = Param(
        "evaluation_metric", "classification|regression|auto", "auto",
        validator=one_of("auto", "classification", "regression"))
    probabilities_col = Param("probabilities_col", "probability column",
                              "probabilities")
    scored_labels_col = Param("scored_labels_col", "predicted label column",
                              "prediction")

    def _transform(self, t: Table) -> Table:
        y = np.asarray(t[self.label_col], dtype=np.float64)
        kind = self.evaluation_metric
        if kind == "auto":
            kind = ("classification"
                    if self.probabilities_col in t else "regression")
        if kind == "classification":
            cols = M.per_instance_classification(y, t[self.probabilities_col])
        else:
            cols = M.per_instance_regression(y, t[self.scored_labels_col])
        return t.with_columns(cols)


class ClassificationEvaluator(Evaluator, HasLabelCol):
    """Scores a transformed table by one classification metric (used by
    TuneHyperparameters / FindBestModel)."""
    metric = Param("metric", "AUC|accuracy|precision|recall|f1", "AUC")
    scores_col = Param("scores_col", "probability column", "probabilities")
    scored_labels_col = Param("scored_labels_col", "prediction column", "prediction")

    def evaluate(self, t: Table) -> float:
        y = np.asarray(t[self.label_col], dtype=np.float64)
        pred = np.asarray(t[self.scored_labels_col], dtype=np.float64)
        scores = None
        if self.scores_col in t:
            s = np.asarray(t[self.scores_col])
            scores = s[:, 1] if s.ndim == 2 and s.shape[1] == 2 else None
        if scores is not None and len(np.unique(y)) <= 2:
            vals, _ = M.binary_metrics(y, scores, y_pred=pred)
        else:
            vals, _ = M.multiclass_metrics(y, pred)
        v = vals.get(self.metric)
        if v is None or (isinstance(v, float) and np.isnan(v)):
            v = vals["accuracy"] if self.metric == "AUC" else vals[self.metric]
        return float(v)


class RegressionEvaluator(Evaluator, HasLabelCol):
    metric = Param("metric", "mse|rmse|r2|mae", "rmse")
    scored_labels_col = Param("scored_labels_col", "prediction column", "prediction")

    def evaluate(self, t: Table) -> float:
        vals = M.regression_metrics(np.asarray(t[self.label_col]),
                                    np.asarray(t[self.scored_labels_col]))
        return float(vals[self.metric])

    @property
    def is_larger_better(self) -> bool:
        return self.metric == "r2"
