"""TrainClassifier / TrainRegressor: wrap any learner with auto-featurization
and label indexing (reference: train/TrainClassifier.scala:49-377,
train/TrainRegressor.scala). The fitted model is featurize -> inner model ->
un-index labels, exactly the reference's TrainedClassifierModel composition.
"""
from __future__ import annotations

import numpy as np

from ..core import (Estimator, Model, Param, Table, HasLabelCol,
                    HasFeaturesCol)
from ..featurize.featurize import Featurize
from ..featurize.value_indexer import ValueIndexer


class TrainClassifier(Estimator, HasLabelCol):
    model = Param("model", "inner classifier estimator", None)
    features_col = Param("features_col", "assembled features column",
                         "__train_features")
    num_features = Param("num_features", "hash-slot override for featurize", 0)
    reindex_label = Param("reindex_label", "index non-contiguous labels", True)

    def _fit(self, t: Table) -> "TrainedClassifierModel":
        inner = self.model
        if inner is None:
            from ..models.linear import LogisticRegression
            inner = LogisticRegression()
        # label indexing (TrainClassifier.scala:91-160)
        label_model = None
        y = t[self.label_col]
        work = t
        if self.reindex_label:
            needs = (y.dtype == object
                     or not np.issubdtype(y.dtype, np.number)
                     or (np.unique(y) != np.arange(len(np.unique(y)))).any())
            if needs:
                label_model = ValueIndexer(
                    input_col=self.label_col,
                    output_col="__label_idx").fit(t)
                work = label_model.transform(t)
                work = work.drop(self.label_col).rename(
                    {"__label_idx": self.label_col})
        feat = Featurize(dense_output=True,  # inner learners take matrices
                         output_col=self.features_col,
                         label_col=self.label_col,
                         num_features=self.num_features).fit(work)
        featurized = feat.transform(work)
        inner = inner.copy({"features_col": self.features_col,
                            "label_col": self.label_col})
        fitted = inner.fit(featurized)
        m = TrainedClassifierModel(label_col=self.label_col)
        m._featurizer, m._model, m._label_model = feat, fitted, label_model
        return m


class TrainedClassifierModel(Model, HasLabelCol):
    def __init__(self, **kw):
        super().__init__(**kw)
        self._featurizer = self._model = self._label_model = None

    def _get_state(self):
        # nested stages persist through the stage-list param mechanism
        return {}

    @property
    def inner_model(self):
        return self._model

    stages = Param("stages", "nested fitted stages (persistence only)", None)

    def _prepare_save(self):
        self.set(stages=[s for s in [self._featurizer, self._model,
                                     self._label_model] if s is not None])

    def _finish_load(self):
        stages = self.get("stages") or []
        self._featurizer = stages[0] if len(stages) > 0 else None
        self._model = stages[1] if len(stages) > 1 else None
        self._label_model = stages[2] if len(stages) > 2 else None

    def _transform(self, t: Table) -> Table:
        out = self._featurizer.transform(t)
        out = self._model.transform(out)
        if self._label_model is not None:
            # un-index predicted labels back to the original values
            levels = self._label_model._levels
            pred = np.asarray(out["prediction"]).astype(int)
            out = out.with_column("scored_labels",
                                  levels[np.clip(pred, 0, len(levels) - 1)])
        else:
            out = out.with_column("scored_labels", out["prediction"])
        return out.drop(self._featurizer.output_col)


class TrainRegressor(Estimator, HasLabelCol):
    model = Param("model", "inner regressor estimator", None)
    features_col = Param("features_col", "assembled features column",
                         "__train_features")
    num_features = Param("num_features", "hash-slot override for featurize", 0)

    def _fit(self, t: Table) -> "TrainedRegressorModel":
        inner = self.model
        if inner is None:
            from ..models.linear import LinearRegression
            inner = LinearRegression()
        feat = Featurize(dense_output=True,  # inner learners take matrices
                         output_col=self.features_col,
                         label_col=self.label_col,
                         num_features=self.num_features).fit(t)
        featurized = feat.transform(t)
        inner = inner.copy({"features_col": self.features_col,
                            "label_col": self.label_col})
        fitted = inner.fit(featurized)
        m = TrainedRegressorModel(label_col=self.label_col)
        m._featurizer, m._model = feat, fitted
        return m


class TrainedRegressorModel(Model, HasLabelCol):
    stages = Param("stages", "nested fitted stages (persistence only)", None)

    def __init__(self, **kw):
        super().__init__(**kw)
        self._featurizer = self._model = None

    def _prepare_save(self):
        self.set(stages=[self._featurizer, self._model])

    def _finish_load(self):
        stages = self.get("stages") or []
        self._featurizer = stages[0] if len(stages) > 0 else None
        self._model = stages[1] if len(stages) > 1 else None

    @property
    def inner_model(self):
        return self._model

    def _transform(self, t: Table) -> Table:
        out = self._featurizer.transform(t)
        out = self._model.transform(out)
        return (out.with_column("scored_labels", out["prediction"])
                   .drop(self._featurizer.output_col))
