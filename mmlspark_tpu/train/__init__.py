from .compute_model_statistics import (ComputeModelStatistics,
                                       ComputePerInstanceStatistics,
                                       ClassificationEvaluator,
                                       RegressionEvaluator)
from .train_classifier import (TrainClassifier, TrainedClassifierModel,
                               TrainRegressor, TrainedRegressorModel)
from . import metrics

__all__ = ["ComputeModelStatistics", "ComputePerInstanceStatistics",
           "ClassificationEvaluator", "RegressionEvaluator", "TrainClassifier",
           "TrainedClassifierModel", "TrainRegressor", "TrainedRegressorModel",
           "metrics"]
